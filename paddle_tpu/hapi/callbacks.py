"""hapi callbacks. Parity: reference python/paddle/hapi/callbacks.py
(Callback base, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks or []

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        if self.verbose and self.steps % self.log_freq == 0:
            items = ", ".join(f"{k}: {np.asarray(v).mean():.4f}"
                              for k, v in (logs or {}).items()
                              if k not in ("batch_size",))
            ips = self.steps / max(time.time() - self._start, 1e-6)
            print(f"Epoch {self.epoch} step {self.steps}: {items} "
                  f"({ips:.1f} step/s)")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {np.asarray(v).mean():.4f}"
                              for k, v in (logs or {}).items()
                              if k not in ("batch_size",))
            print(f"Eval: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = np.inf if mode == "min" else -np.inf

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).mean())
        improved = (cur < self.best - self.min_delta) if self.mode == "min" \
            else (cur > self.best + self.min_delta)
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class ReduceLROnPlateau(Callback):
    """Parity: callbacks.ReduceLROnPlateau — scale the optimizer LR when
    the monitored metric stops improving."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.verbose = verbose
        self.min_delta = float(min_delta)
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        better_is_less = mode == "min" or (mode == "auto"
                                           and "acc" not in monitor)
        self._cmp = ((lambda a, b: a < b - self.min_delta)
                     if better_is_less else
                     (lambda a, b: a > b + self.min_delta))
        self._best = None
        self._wait = 0
        self._cool = 0

    def on_eval_end(self, logs=None):
        self._step(logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._step(logs or {})

    def _step(self, logs):
        import numpy as np
        val = logs.get(self.monitor)
        if val is None:
            return
        val = float(np.ravel(val)[0])
        if self._cool > 0:
            self._cool -= 1
            return
        if self._best is None or self._cmp(val, self._best):
            self._best = val
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                try:
                    lr = opt.get_lr()
                    new = max(lr * self.factor, self.min_lr)
                    if new < lr:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr -> {new:.3e}")
                except RuntimeError:
                    pass  # LRScheduler-driven optimizer owns its LR
            self._wait = 0
            self._cool = self.cooldown


class VisualDL(Callback):
    """Parity: callbacks.VisualDL. The visualdl package does not ship in
    the TPU image; this writes the same scalar stream as JSONL next to
    the would-be logdir so runs remain inspectable."""

    def __init__(self, log_dir="./log"):
        import os
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = open(os.path.join(log_dir, "scalars.jsonl"), "a")
        self._step = 0

    def _write(self, tag, logs):
        import json
        import numpy as np
        rec = {"step": self._step, "tag": tag}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(np.ravel(v)[0])
            except (TypeError, ValueError):
                pass
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 100 == 0:
            self._write("train", logs)

    def on_epoch_end(self, epoch, logs=None):
        self._write("train_epoch", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """Parity: callbacks.WandbCallback — requires the wandb package,
    which the zero-egress TPU image does not ship."""

    def __init__(self, *args, **kwargs):
        raise ImportError(
            "wandb is not installed in the TPU image (zero egress); use "
            "VisualDL (JSONL scalars) or ProgBarLogger instead")


__all__ += ["ReduceLROnPlateau", "VisualDL", "WandbCallback"]
