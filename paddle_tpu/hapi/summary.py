"""paddle.summary / paddle.flops — model inspection.

Parity: reference `python/paddle/hapi/model_summary.py` (summary) and
`python/paddle/hapi/dynamic_flops.py` (flops): per-layer shape/param
table from a hooked forward pass, and a FLOPs estimate for the common
layer types.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary", "flops"]


def _zeros_input(input_size, dtypes=None):
    import jax.numpy as jnp
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        shapes = input_size
    else:
        shapes = [tuple(input_size)]
    dt = dtypes or ["float32"] * len(shapes)
    return [Tensor(jnp.zeros(tuple(int(d) for d in s), jnp.dtype(t)))
            for s, t in zip(shapes, dt)]


def summary(net, input_size=None, dtypes=None, input=None):
    """Per-layer output-shape/param table (parity: hapi.summary)."""
    rows: List[dict] = []
    hooks = []

    def make_hook(name, layer):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else []
            n_params = sum(int(np.prod(p.shape))
                           for p in l._parameters.values()
                           if p is not None)
            rows.append({"name": name or type(l).__name__,
                         "type": type(l).__name__,
                         "output_shape": shape, "params": n_params})
        return hook

    for name, layer in net.named_sublayers(include_self=True):
        if not layer._sub_layers:  # leaves only (incl. a leaf root layer)
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))
    try:
        args = [input] if input is not None else _zeros_input(input_size,
                                                              dtypes)
        net(*args)
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    width = 72
    print("-" * width)
    print(f"{'Layer (type)':<32}{'Output Shape':<24}{'Param #':>14}")
    print("=" * width)
    for r in rows:
        print(f"{(r['name'] + ' (' + r['type'] + ')')[:31]:<32}"
              f"{str(r['output_shape']):<24}{r['params']:>14,}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}


def _layer_flops(layer, inputs, outputs):
    """FLOPs for the common layer types (parity: dynamic_flops.py
    count_* registry)."""
    from ..nn import Conv2D, Linear
    out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
    if not isinstance(out, Tensor):
        return 0
    out_elems = int(np.prod(out.shape))
    name = type(layer).__name__
    if name in ("Linear", "ColumnParallelLinear", "RowParallelLinear"):
        in_f = layer.weight.shape[0]
        return 2 * out_elems * int(in_f)
    if name in ("Conv2D", "Conv1D", "Conv3D"):
        w = layer.weight
        kernel_elems = int(np.prod(w.shape[1:]))  # cin/groups * k...
        return 2 * out_elems * kernel_elems
    if "Norm" in name:
        return 2 * out_elems
    if name.lower() in ("relu", "gelu", "sigmoid", "tanh", "softmax",
                        "silu", "swish", "leakyrelu", "elu", "hardswish"):
        return out_elems
    if "Pool" in name:
        return out_elems
    return 0


def flops(net, input_size, custom_ops: Optional[Dict] = None,
          print_detail=False):
    """Total forward FLOPs estimate (parity: paddle.flops)."""
    total = [0]
    detail = []
    hooks = []
    custom_ops = custom_ops or {}

    def make_hook(name, layer):
        def hook(l, inputs, outputs):
            fn = custom_ops.get(type(l))
            n = fn(l, inputs, outputs) if fn else _layer_flops(l, inputs,
                                                              outputs)
            total[0] += n
            detail.append((name or type(l).__name__, n))
        return hook

    for name, layer in net.named_sublayers(include_self=True):
        if not layer._sub_layers:
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))
    try:
        net(*_zeros_input(input_size))
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        for name, n in detail:
            print(f"{name:<40}{n:>16,}")
    print(f"Total Flops: {total[0]:,}     "
          f"Total Params: {sum(int(np.prod(p.shape)) for p in net.parameters()):,}")
    return total[0]
