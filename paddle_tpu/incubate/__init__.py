"""paddle.incubate — fused layers + ASP (2:4 sparsity).

Parity: reference `python/paddle/incubate/` — nn fused transformer layers
(`incubate/nn/layer/fused_transformer.py`), fused functionals
(`incubate/nn/functional/`), and ASP (`incubate/asp/`).
"""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage, LarsMomentum  # noqa: F401
from .nn.functional import softmax_mask_fuse_upper_triangle  # noqa: F401

__all__ = ["nn", "asp", "optimizer", "LookAhead", "ModelAverage",
           "LarsMomentum", "softmax_mask_fuse_upper_triangle"]


# graph/segment surface (parity: incubate exports; the implementations
# live in paddle.geometric, as in the reference where incubate re-exports)
from ..geometric import (  # noqa: F401
    segment_sum, segment_mean, segment_max, segment_min)
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one taped op (parity: incubate
    softmax_mask_fuse over fused_softmax_mask kernel)."""
    from ..ops.dispatch import apply_op
    import jax
    return apply_op("softmax_mask_fuse",
                    lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask)


def identity_loss(x, reduction="none", name=None):
    """Mark a tensor as a loss output (parity: incubate identity_loss —
    an IPU-era marker; semantics here are the chosen reduction)."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 0):
        return x.sum()
    return x.mean()


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbor sampling over a CSC graph (parity: incubate
    graph_khop_sampler). Host-side (data-dependent output sizes)."""
    import numpy as np
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    r = np.asarray(row._data if hasattr(row, "_data") else row)
    cp = np.asarray(colptr._data if hasattr(colptr, "_data") else colptr)
    frontier = np.asarray(input_nodes._data if hasattr(input_nodes, "_data")
                          else input_nodes).reshape(-1)
    from ..framework.random import rng_key
    import jax as _jax
    rng = np.random.RandomState(
        int(_jax.random.randint(rng_key(), (), 0, 2**31 - 1)))
    edge_src, edge_dst = [], []
    nodes = list(frontier.tolist())
    seen = set(nodes)
    for k in sample_sizes:
        nxt = []
        for v in frontier:
            neigh = r[cp[v]:cp[v + 1]]
            if k >= 0 and neigh.size > k:
                neigh = rng.choice(neigh, size=k, replace=False)
            for u in neigh:
                edge_src.append(int(u))
                edge_dst.append(int(v))
                if int(u) not in seen:
                    seen.add(int(u))
                    nodes.append(int(u))
                    nxt.append(int(u))
        frontier = np.asarray(nxt, np.int64)
    remap = {n: i for i, n in enumerate(nodes)}
    es = np.asarray([remap[s] for s in edge_src], np.int64)
    ed = np.asarray([remap[d] for d in edge_dst], np.int64)
    # reindex_x: positions of input_nodes in the sampled-node list — the
    # frontier seeds the list, so these are the first len(input) slots
    n_in = np.asarray(input_nodes._data if hasattr(input_nodes, "_data")
                      else input_nodes).reshape(-1).shape[0]
    outs = (Tensor(jnp.asarray(es)), Tensor(jnp.asarray(ed)),
            Tensor(jnp.asarray(np.asarray(nodes, np.int64))),
            Tensor(jnp.asarray(np.arange(n_in, dtype=np.int64))))
    if return_eids:
        outs = outs + (Tensor(jnp.asarray(
            np.arange(es.shape[0], dtype=np.int64))),)
    return outs


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           edge_weight=None, name=None):
    """One-hop neighbor sampling, uniform or weight-proportional (parity:
    incubate graph_sample_neighbors; geometric.weighted_sample_neighbors
    delegates here with edge_weight). Host-side."""
    import numpy as np
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    r = np.asarray(row._data if hasattr(row, "_data") else row)
    cp = np.asarray(colptr._data if hasattr(colptr, "_data") else colptr)
    nodes = np.asarray(input_nodes._data if hasattr(input_nodes, "_data")
                       else input_nodes).reshape(-1)
    w = None if edge_weight is None else np.asarray(
        edge_weight._data if hasattr(edge_weight, "_data")
        else edge_weight).reshape(-1)
    ei = None if eids is None else np.asarray(
        eids._data if hasattr(eids, "_data") else eids).reshape(-1)
    from ..framework.random import rng_key
    import jax as _jax
    rng = np.random.RandomState(
        int(_jax.random.randint(rng_key(), (), 0, 2**31 - 1)))
    out, counts, out_eids = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        idx = np.arange(lo, hi)
        if sample_size >= 0 and idx.size > sample_size:
            if w is not None:
                ws = w[idx]
                tot = ws.sum()
                if tot <= 0:          # degenerate weights: fall back to
                    p = None          # uniform rather than NaN probs
                else:
                    p = ws / tot
                    nz = int((p > 0).sum())
                    if nz < sample_size:
                        p = None
                idx = rng.choice(idx, size=sample_size, replace=False, p=p)
            else:
                idx = rng.choice(idx, size=sample_size, replace=False)
        out.extend(int(u) for u in r[idx])
        counts.append(idx.size)
        if return_eids:
            src_e = ei if ei is not None else np.arange(r.shape[0])
            out_eids.extend(int(e) for e in src_e[idx])
    res = (Tensor(jnp.asarray(np.asarray(out, np.int64))),
           Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    if return_eids:
        res = res + (Tensor(jnp.asarray(np.asarray(out_eids, np.int64))),)
    return res


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex a sampled subgraph to local ids (parity: incubate
    graph_reindex). Host-side."""
    import numpy as np
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    xs = np.asarray(x._data if hasattr(x, "_data") else x).reshape(-1)
    nb = np.asarray(neighbors._data if hasattr(neighbors, "_data")
                    else neighbors).reshape(-1)
    ct = np.asarray(count._data if hasattr(count, "_data")
                    else count).reshape(-1)
    remap = {int(n): i for i, n in enumerate(xs)}
    order = list(xs.tolist())
    for u in nb:
        if int(u) not in remap:
            remap[int(u)] = len(order)
            order.append(int(u))
    re_nb = np.asarray([remap[int(u)] for u in nb], np.int64)
    re_src = np.repeat(np.arange(ct.shape[0]), ct).astype(np.int64)
    return (Tensor(jnp.asarray(re_nb)), Tensor(jnp.asarray(re_src)),
            Tensor(jnp.asarray(np.asarray(order, np.int64))))


from . import inference  # noqa: F401,E402


from . import autograd  # noqa: F401,E402
from . import autotune  # noqa: F401,E402
__all__ += ["autograd", "autotune"]

from . import distributed  # noqa: F401,E402
__all__ += ["distributed"]
