"""paddle.incubate — fused layers + ASP (2:4 sparsity).

Parity: reference `python/paddle/incubate/` — nn fused transformer layers
(`incubate/nn/layer/fused_transformer.py`), fused functionals
(`incubate/nn/functional/`), and ASP (`incubate/asp/`).
"""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .nn.functional import softmax_mask_fuse_upper_triangle  # noqa: F401

__all__ = ["nn", "asp", "optimizer", "LookAhead", "ModelAverage",
           "softmax_mask_fuse_upper_triangle"]
