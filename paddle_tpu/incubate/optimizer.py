"""incubate.optimizer — LookAhead, ModelAverage, LarsMomentum.

Parity: reference `python/paddle/incubate/optimizer/lookahead.py`
(LookAhead:24 — slow/fast weights, slow = slow + alpha*(fast - slow)
every k steps), `modelaverage.py` (ModelAverage — running parameter
average applied for eval via apply()/restore()), and
`lars_momentum.py` + `phi/kernels/cpu/lars_momentum_kernel.cc:66-73`
(LARS trust-ratio local learning rate).

TPU-native: the slow/average buffers are device arrays updated by the
same jnp expressions the inner optimizer uses; everything stays on device
(no host copies in the step path)."""
from __future__ import annotations

import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage", "LarsMomentum",
           "LarsMomentumOptimizer"]


class LarsMomentum(Optimizer):
    """Momentum with LARS layer-wise trust-ratio learning rates.

    Update (parity: reference lars_momentum_kernel.cc:66-73):
        local_lr = lr                                  # default
        if lars_weight_decay > 0 and |p| > 0 and |g| > 0:
            local_lr = lr * lars_coeff * |p|
                       / (|g| + lars_weight_decay * |p| + epsilon)
        v = mu * v + local_lr * (g + lars_weight_decay * p)
        p = p - v

    `exclude_from_weight_decay` is a list of name substrings whose
    parameters use lars_weight_decay = 0 (and hence plain momentum),
    matching LarsMomentumOptimizer (incubate/optimizer/lars_momentum.py).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005, epsilon=0.0,
                 exclude_from_weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name, multi_precision)
        self._momentum = float(momentum)
        self._lars_coeff = float(lars_coeff)
        self._lars_weight_decay = float(lars_weight_decay)
        self._eps = float(epsilon)
        self._rescale_grad = float(rescale_grad)
        self._exclude = list(exclude_from_weight_decay or [])

    def _wd_for(self, p):
        name = getattr(p, "name", "") or ""
        if any(token in name for token in self._exclude):
            return 0.0
        return self._lars_weight_decay

    def _apply_one(self, idx, p, g, lr):
        m = self._master(idx, p)
        g = g.astype(m.dtype) * self._rescale_grad
        wd = self._wd_for(p)
        p_norm = jnp.sqrt(jnp.sum(m.astype(jnp.float32) ** 2))
        g_norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        if wd > 0:
            trust = jnp.where(
                (p_norm > 0) & (g_norm > 0),
                self._lars_coeff * p_norm
                / (g_norm + wd * p_norm + self._eps),
                1.0).astype(m.dtype)
        else:
            trust = 1.0
        vel = self._acc("velocity", idx, m)
        vel = self._momentum * vel + (lr * trust) * (g + wd * m)
        self._set_acc("velocity", idx, vel)
        self._writeback(idx, p, m - vel)


# reference class name (python/paddle/incubate/optimizer/lars_momentum.py)
LarsMomentumOptimizer = LarsMomentum


class LookAhead:
    """k-step lookahead over an inner optimizer."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = None

    @property
    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        if self._slow is None:
            self._slow = [p._data for p in self._params]
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            masters = getattr(self.inner_optimizer, "_master_weights", {})
            for i, p in enumerate(self._params):
                slow = (self._slow[i].astype(jnp.float32)
                        + self.alpha * (p._data.astype(jnp.float32)
                                        - self._slow[i].astype(jnp.float32)))
                self._slow[i] = slow.astype(p._data.dtype)
                p._data = self._slow[i]
                if i in masters:
                    # keep the inner optimizer's fp32 master in sync or the
                    # next step would overwrite the pullback
                    masters[i] = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # reference contract: no clear_grad, return (ops, params_grads)
        loss.backward()
        params = self.inner_optimizer._parameter_list
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        self.step()
        return [], params_grads

    def state_dict(self):
        return {"inner": getattr(self.inner_optimizer, "state_dict",
                                 dict)(),
                "slow": self._slow, "step_num": self._step_num}

    def set_state_dict(self, state):
        inner_sd = state.get("inner")
        if inner_sd and hasattr(self.inner_optimizer, "set_state_dict"):
            self.inner_optimizer.set_state_dict(inner_sd)
        self._slow = state.get("slow")
        self._step_num = int(state.get("step_num", 0))


class ModelAverage:
    """Running average of parameters, swapped in for evaluation.

    average_window_rate bounds the window like the reference; apply()
    swaps averaged weights in (optionally inside a `with`), restore()
    swaps the trained weights back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage requires the parameter list")
        self._params = list(parameters)
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum = [jnp.zeros(tuple(p.shape), jnp.float32)
                     for p in self._params]
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current parameters into the running average
        (call after optimizer.step())."""
        window = max(self.min_w,
                     min(self.max_w, int(self.rate * (self._count + 1))))
        if self._count >= window:
            decay = 1.0 - 1.0 / window
            self._sum = [s * decay for s in self._sum]
            self._count = int(self._count * decay)
        self._sum = [s + p._data.astype(jnp.float32)
                     for s, p in zip(self._sum, self._params)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap the averaged parameters in (context-manager friendly)."""
        if self._count == 0:
            return self
        if self._backup is not None:
            # already applied: refuse the second swap (it would back up
            # the averaged weights and lose the trained ones) but honor
            # the caller's restore intent for `with` usage
            self._need_restore = need_restore
            return self
        self._backup = [p._data for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._data = (s / self._count).astype(p._data.dtype)
        self._need_restore = need_restore
        return self

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._data = b
            self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self, "_need_restore", True):
            self.restore()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        raise RuntimeError(
            "ModelAverage tracks another optimizer's parameters; call "
            "step() after the training optimizer's step()")


# reference incubate.optimizer re-exports LBFGS (its __all__ is ['LBFGS'])
from ..optimizer.optimizer import LBFGS  # noqa: E402

__all__ += ["LBFGS", "functional"]

from . import optimizer_functional as functional  # noqa: E402
