"""incubate.optimizer — LookAhead and ModelAverage wrappers.

Parity: reference `python/paddle/incubate/optimizer/lookahead.py`
(LookAhead:24 — slow/fast weights, slow = slow + alpha*(fast - slow)
every k steps) and `modelaverage.py` (ModelAverage — running parameter
average applied for eval via apply()/restore()).

TPU-native: the slow/average buffers are device arrays updated by the
same jnp expressions the inner optimizer uses; everything stays on device
(no host copies in the step path)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k-step lookahead over an inner optimizer."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = None

    @property
    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        if self._slow is None:
            self._slow = [p._data for p in self._params]
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            masters = getattr(self.inner_optimizer, "_master_weights", {})
            for i, p in enumerate(self._params):
                slow = (self._slow[i].astype(jnp.float32)
                        + self.alpha * (p._data.astype(jnp.float32)
                                        - self._slow[i].astype(jnp.float32)))
                self._slow[i] = slow.astype(p._data.dtype)
                p._data = self._slow[i]
                if i in masters:
                    # keep the inner optimizer's fp32 master in sync or the
                    # next step would overwrite the pullback
                    masters[i] = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # reference contract: no clear_grad, return (ops, params_grads)
        loss.backward()
        params = self.inner_optimizer._parameter_list
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        self.step()
        return [], params_grads

    def state_dict(self):
        return {"inner": getattr(self.inner_optimizer, "state_dict",
                                 dict)(),
                "slow": self._slow, "step_num": self._step_num}

    def set_state_dict(self, state):
        inner_sd = state.get("inner")
        if inner_sd and hasattr(self.inner_optimizer, "set_state_dict"):
            self.inner_optimizer.set_state_dict(inner_sd)
        self._slow = state.get("slow")
        self._step_num = int(state.get("step_num", 0))


class ModelAverage:
    """Running average of parameters, swapped in for evaluation.

    average_window_rate bounds the window like the reference; apply()
    swaps averaged weights in (optionally inside a `with`), restore()
    swaps the trained weights back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage requires the parameter list")
        self._params = list(parameters)
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum = [jnp.zeros(tuple(p.shape), jnp.float32)
                     for p in self._params]
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current parameters into the running average
        (call after optimizer.step())."""
        window = max(self.min_w,
                     min(self.max_w, int(self.rate * (self._count + 1))))
        if self._count >= window:
            decay = 1.0 - 1.0 / window
            self._sum = [s * decay for s in self._sum]
            self._count = int(self._count * decay)
        self._sum = [s + p._data.astype(jnp.float32)
                     for s, p in zip(self._sum, self._params)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap the averaged parameters in (context-manager friendly)."""
        if self._count == 0:
            return self
        if self._backup is not None:
            # already applied: refuse the second swap (it would back up
            # the averaged weights and lose the trained ones) but honor
            # the caller's restore intent for `with` usage
            self._need_restore = need_restore
            return self
        self._backup = [p._data for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._data = (s / self._count).astype(p._data.dtype)
        self._need_restore = need_restore
        return self

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._data = b
            self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self, "_need_restore", True):
            self.restore()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        raise RuntimeError(
            "ModelAverage tracks another optimizer's parameters; call "
            "step() after the training optimizer's step()")
