"""incubate.inference — decorator marking a predictor function (parity:
reference incubate/inference: TensorRT-conversion decorator). On TPU the
conversion target is jit.to_static + StableHLO export; the decorator
compiles the wrapped callable on first use."""
from __future__ import annotations

__all__ = ["enable_inference_mode"]


def enable_inference_mode(func=None, **kwargs):
    def deco(f):
        from ..jit.api import to_static
        return to_static(f)
    if func is not None:
        return deco(func)
    return deco
