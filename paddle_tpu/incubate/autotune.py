"""incubate.autotune — kernel/layout/dataloader autotuning config.

Parity: reference `python/paddle/incubate/autotune.py` set_config (JSON
or dict with kernel/layout/dataloader sections). TPU-native: the kernel
section maps onto the Pallas block-size autotuner
(paddle_tpu.kernels.autotune); layout/dataloader tuning collapse into
XLA/the C++ DataLoader workers.
"""
import json

__all__ = ["set_config"]

_config = {"kernel": {"enable": True, "tuning_range": [1, 10]},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}


def set_config(config=None):
    global _config
    if config is None:
        return dict(_config)
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for k, v in config.items():
        _config.setdefault(k, {}).update(v)
    if "kernel" in config:
        from ..kernels import autotune as _at
        enable = bool(config["kernel"].get("enable", True))
        if hasattr(_at, "set_enabled"):
            _at.set_enabled(enable)
    return dict(_config)
