"""incubate.optimizer.functional — functional quasi-Newton minimizers.

Parity: reference `python/paddle/incubate/optimizer/functional/`
(minimize_bfgs / minimize_lbfgs: line-search quasi-Newton over a scalar
objective, returning (is_converge, num_func_calls, position, f, g[, Hk])).
TPU-native: the objective is jax-differentiable; updates are jnp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _as_arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _value_and_grad(objective_func):
    def f(x):
        out = objective_func(Tensor(x))
        return _as_arr(out).reshape(())
    return jax.value_and_grad(f)


def _backtrack(fg, x, d, f0, g0, max_ls=20):
    """Armijo backtracking line search."""
    alpha = 1.0
    c1 = 1e-4
    gd = float(jnp.vdot(g0, d))
    calls = 0
    for _ in range(max_ls):
        f1, _ = fg(x + alpha * d)
        calls += 1
        if float(f1) <= float(f0) + c1 * alpha * gd:
            return alpha, calls
        alpha *= 0.5
    return alpha, calls


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None, line_search_fn=
                  "strong_wolfe", max_line_search_iters=50, dtype="float32",
                  name=None):
    fg = _value_and_grad(objective_func)
    x = _as_arr(initial_position).astype(dtype)
    n = x.size
    H = (jnp.eye(n, dtype=x.dtype)
         if initial_inverse_hessian_estimate is None
         else _as_arr(initial_inverse_hessian_estimate))
    f, g = fg(x)
    calls = 1
    converged = False
    for _ in range(max_iters):
        if float(jnp.max(jnp.abs(g))) < tolerance_grad:
            converged = True
            break
        d = -(H @ g.reshape(-1)).reshape(x.shape)
        alpha, c = _backtrack(fg, x, d, f, g, max_line_search_iters)
        calls += c
        s = alpha * d
        x_new = x + s
        f_new, g_new = fg(x_new)
        calls += 1
        y = (g_new - g).reshape(-1)
        sv = s.reshape(-1)
        sy = float(jnp.vdot(sv, y))
        if abs(float(f_new - f)) < tolerance_change:
            x, f, g = x_new, f_new, g_new
            converged = True
            break
        if sy > 1e-10:
            rho = 1.0 / sy
            I = jnp.eye(n, dtype=x.dtype)
            V = I - rho * jnp.outer(sv, y)
            H = V @ H @ V.T + rho * jnp.outer(sv, sv)
        x, f, g = x_new, f_new, g_new
    return (Tensor(jnp.asarray(converged)), Tensor(jnp.asarray(calls)),
            Tensor(x), Tensor(f), Tensor(g), Tensor(H))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7, tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   dtype="float32", name=None):
    fg = _value_and_grad(objective_func)
    x = _as_arr(initial_position).astype(dtype)
    f, g = fg(x)
    calls = 1
    s_hist, y_hist = [], []
    converged = False
    for _ in range(max_iters):
        if float(jnp.max(jnp.abs(g))) < tolerance_grad:
            converged = True
            break
        # two-loop recursion
        q = g.reshape(-1)
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / float(jnp.vdot(s, y))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        gamma = 1.0
        if s_hist:
            gamma = float(jnp.vdot(s_hist[-1], y_hist[-1])
                          / jnp.vdot(y_hist[-1], y_hist[-1]))
        r = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.vdot(y, r))
            r = r + s * (a - b)
        d = -r.reshape(x.shape)
        alpha, c = _backtrack(fg, x, d, f, g, max_line_search_iters)
        calls += c
        s = alpha * d
        x_new = x + s
        f_new, g_new = fg(x_new)
        calls += 1
        yv = (g_new - g).reshape(-1)
        if float(jnp.vdot(s.reshape(-1), yv)) > 1e-10:
            s_hist.append(s.reshape(-1))
            y_hist.append(yv)
            if len(s_hist) > history_size:
                s_hist.pop(0)
                y_hist.pop(0)
        if abs(float(f_new - f)) < tolerance_change:
            x, f, g = x_new, f_new, g_new
            converged = True
            break
        x, f, g = x_new, f_new, g_new
    return (Tensor(jnp.asarray(converged)), Tensor(jnp.asarray(calls)),
            Tensor(x), Tensor(f), Tensor(g))
