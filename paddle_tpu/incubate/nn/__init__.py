"""incubate.nn — fused transformer layers.

Parity: reference `python/paddle/incubate/nn/layer/fused_transformer.py`
(FusedMultiHeadAttention:30, FusedFeedForward, FusedTransformerEncoderLayer).
On TPU the "fusion" is XLA's job; the layers keep the reference's
weight layout (qkv packed (3, H, D, hidden)) so checkpoints map 1:1.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from . import functional  # noqa: F401
from ...core.tensor import Tensor
from ...nn.initializer import XavierUniform
from ...nn.layer.layers import Layer

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Parity: fused_transformer.py FusedMultiHeadAttention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self.dropout_rate = dropout_rate
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim),
            default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            (3, num_heads, self.head_dim), is_bias=True)
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter((embed_dim,), is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=None)
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return functional.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            attn_mask=attn_mask, dropout_rate=0.0,
            ln_epsilon=self._epsilon, num_heads=self.num_heads)


class FusedFeedForward(Layer):
    """Parity: fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter((dim_feedforward,),
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter((d_model,), is_bias=True)
        self.ln2_scale = self.create_parameter((d_model,))
        self.ln2_bias = self.create_parameter((d_model,), is_bias=True)

    def forward(self, src, cache=None):
        return functional.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            activation=self._act, ln2_epsilon=self._epsilon,
            pre_layer_norm=self.normalize_before)


class FusedTransformerEncoderLayer(Layer):
    """Parity: fused_transformer.py FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


def _Ones():
    from ...nn.initializer import Constant
    return Constant(1.0)


class FusedLinear(Layer):
    """Parity: incubate.nn.FusedLinear (fused_matmul_bias layer)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)
        self._tw = transpose_weight

    def forward(self, x):
        from .functional import fused_matmul_bias
        return fused_matmul_bias(x, self.weight, self.bias,
                                 transpose_y=self._tw)


class FusedDropoutAdd(Layer):
    """Parity: incubate.nn.FusedDropoutAdd — y = x + dropout(y_in)...
    precisely dropout(x) + y in the reference."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from .functional import fused_dropout_add
        return fused_dropout_add(x, y, p=self.p, training=self.training,
                                 mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """Parity: incubate.nn.FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr, default_initializer=_Ones())
        self.ln_bias = self.create_parameter((embed_dim,), attr=bias_attr,
                                             is_bias=True)
        self.linear_bias = self.create_parameter((embed_dim,), is_bias=True)
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm
        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedMultiTransformer(Layer):
    """Parity: incubate.nn.FusedMultiTransformer — the serving decoder
    stack owning per-layer weight lists, forwarded through
    functional.fused_multi_transformer."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, qkv_weight_attrs=None,
                 linear_weight_attrs=None, ffn_ln_scale_attrs=None,
                 ffn1_weight_attrs=None, ffn2_weight_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, norm_type="layernorm", name=None):
        super().__init__()
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        head_dim = embed_dim // num_heads
        self._cfg = dict(pre_layer_norm=normalize_before, epsilon=epsilon,
                         activation=activation, trans_qkvw=trans_qkvw,
                         norm_type=norm_type, dropout_rate=dropout_rate)
        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            add = self.add_parameter
            add(f"ln_scale_{i}", self.create_parameter(
                (embed_dim,), default_initializer=_Ones()))
            add(f"ln_bias_{i}", self.create_parameter((embed_dim,),
                                                      is_bias=True))
            add(f"qkv_weight_{i}", self.create_parameter(
                (3, num_heads, head_dim, embed_dim)))
            add(f"qkv_bias_{i}", self.create_parameter(
                (3, num_heads, head_dim), is_bias=True))
            add(f"linear_weight_{i}", self.create_parameter(
                (embed_dim, embed_dim)))
            add(f"linear_bias_{i}", self.create_parameter((embed_dim,),
                                                          is_bias=True))
            add(f"ffn_ln_scale_{i}", self.create_parameter(
                (embed_dim,), default_initializer=_Ones()))
            add(f"ffn_ln_bias_{i}", self.create_parameter((embed_dim,),
                                                          is_bias=True))
            add(f"ffn1_weight_{i}", self.create_parameter(
                (embed_dim, dim_feedforward)))
            add(f"ffn1_bias_{i}", self.create_parameter(
                (dim_feedforward,), is_bias=True))
            add(f"ffn2_weight_{i}", self.create_parameter(
                (dim_feedforward, embed_dim)))
            add(f"ffn2_bias_{i}", self.create_parameter((embed_dim,),
                                                        is_bias=True))
            self.ln_scales.append(getattr(self, f"ln_scale_{i}"))
            self.ln_biases.append(getattr(self, f"ln_bias_{i}"))
            self.qkv_weights.append(getattr(self, f"qkv_weight_{i}"))
            self.qkv_biases.append(getattr(self, f"qkv_bias_{i}"))
            self.linear_weights.append(getattr(self, f"linear_weight_{i}"))
            self.linear_biases.append(getattr(self, f"linear_bias_{i}"))
            self.ffn_ln_scales.append(getattr(self, f"ffn_ln_scale_{i}"))
            self.ffn_ln_biases.append(getattr(self, f"ffn_ln_bias_{i}"))
            self.ffn1_weights.append(getattr(self, f"ffn1_weight_{i}"))
            self.ffn1_biases.append(getattr(self, f"ffn1_bias_{i}"))
            self.ffn2_weights.append(getattr(self, f"ffn2_weight_{i}"))
            self.ffn2_biases.append(getattr(self, f"ffn2_bias_{i}"))

    def forward(self, x, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        from .functional import fused_multi_transformer
        return fused_multi_transformer(
            x, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            attn_mask=attn_mask, cache_kvs=caches, **self._cfg)


__all__ += ["FusedLinear", "FusedDropoutAdd",
            "FusedBiasDropoutResidualLayerNorm", "FusedMultiTransformer"]
