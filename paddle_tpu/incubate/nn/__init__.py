"""incubate.nn — fused transformer layers.

Parity: reference `python/paddle/incubate/nn/layer/fused_transformer.py`
(FusedMultiHeadAttention:30, FusedFeedForward, FusedTransformerEncoderLayer).
On TPU the "fusion" is XLA's job; the layers keep the reference's
weight layout (qkv packed (3, H, D, hidden)) so checkpoints map 1:1.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from . import functional  # noqa: F401
from ...core.tensor import Tensor
from ...nn.initializer import XavierUniform
from ...nn.layer.layers import Layer

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Parity: fused_transformer.py FusedMultiHeadAttention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self.dropout_rate = dropout_rate
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim),
            default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            (3, num_heads, self.head_dim), is_bias=True)
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter((embed_dim,), is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=None)
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return functional.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            attn_mask=attn_mask, dropout_rate=0.0,
            ln_epsilon=self._epsilon, num_heads=self.num_heads)


class FusedFeedForward(Layer):
    """Parity: fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter((dim_feedforward,),
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter((d_model,), is_bias=True)
        self.ln2_scale = self.create_parameter((d_model,))
        self.ln2_bias = self.create_parameter((d_model,), is_bias=True)

    def forward(self, src, cache=None):
        return functional.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            activation=self._act, ln2_epsilon=self._epsilon,
            pre_layer_norm=self.normalize_before)


class FusedTransformerEncoderLayer(Layer):
    """Parity: fused_transformer.py FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
