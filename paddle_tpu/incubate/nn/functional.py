"""incubate.nn.functional — fused op surface.

Parity: reference `python/paddle/incubate/nn/functional/` —
fused_multi_head_attention, fused_feedforward, fused_rms_norm,
fused_layer_norm, fused_rotary_position_embedding, fused_dropout_add,
swiglu, fused_bias_act, softmax_mask_fuse_upper_triangle (the
`phi/kernels/fusion/` pack, SURVEY.md A.2).

TPU-native: these are jnp compositions in ONE dispatch-funnel op each —
XLA's fusion pass is the "fused kernel"; keeping each as a single taped
op preserves the reference's op-granularity for profiling/AMP hooks while
letting the compiler fuse across them anyway. The flash-attention path
reuses the Pallas kernel where eligible.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...ops.dispatch import apply_op

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_rms_norm", "fused_layer_norm",
           "fused_rotary_position_embedding", "fused_dropout_add", "swiglu",
           "fused_bias_act", "fused_linear", "fused_linear_activation",
           "softmax_mask_fuse_upper_triangle",
           "masked_multihead_attention", "block_multihead_attention"]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, residual=None, bias=None, **kw):
    """bias-add + residual-add + rms_norm in one taped op
    (fusion/rms_norm_kernel)."""
    def _f(a, w, *rest):
        rest = list(rest)
        nb = rest.pop(0) if norm_bias is not None else None
        res = rest.pop(0) if residual is not None else None
        b = rest.pop(0) if bias is not None else None
        if b is not None:
            a = a + b
        if res is not None:
            a = a + res
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon))
        out = out.astype(a.dtype) * w
        if nb is not None:
            out = out + nb
        # Only emit the residual-chain tensor when a residual/bias was
        # actually added: with neither, `a` IS the input, and returning
        # it forces XLA to materialize an un-aliasable copy — measured
        # on chip as a full extra HBM pass (339 vs 455 GB/s at
        # 32768x4096).
        if res is None and b is None:
            return out
        return out, a

    args = [x, norm_weight]
    if norm_bias is not None:
        args.append(norm_bias)
    if residual is not None:
        args.append(residual)
    if bias is not None:
        args.append(bias)
    r = apply_op("fused_rms_norm", _f, *args)
    if residual is None and bias is None:
        return r
    out, res_out = r
    return (out, res_out) if residual is not None else out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, residual=None, bias=None, **kw):
    """bias+residual+layernorm (fusion/fused_layernorm_kernel)."""
    def _f(a, w, b, *rest):
        rest = list(rest)
        res = rest.pop(0) if residual is not None else None
        pre_b = rest.pop(0) if bias is not None else None
        if pre_b is not None:
            a = a + pre_b
        if res is not None:
            a = a + res
        mu = jnp.mean(a, axis=-1, keepdims=True)
        var = jnp.var(a, axis=-1, keepdims=True)
        out = (a - mu) * jax.lax.rsqrt(var + epsilon)
        out = out * w + b
        # see fused_rms_norm: don't force an un-aliasable copy of the
        # input as a second output when nothing was added to it
        if res is None and pre_b is None:
            return out
        return out, a

    args = [x, norm_weight, norm_bias]
    if residual is not None:
        args.append(residual)
    if bias is not None:
        args.append(bias)
    r = apply_op("fused_layer_norm", _f, *args)
    if residual is None and bias is None:
        return r
    out, res_out = r
    return (out, res_out) if residual is not None else out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    **kw):
    """RoPE applied to q/k[/v] in one op (fusion/fused_rope)."""
    from ...models.llama import apply_rotary

    def _rope(x, c, s):
        # c/s arrive as (S, D/2) or (1, S, 1, D/2); canonicalize to (S, D/2)
        cc = c.reshape(c.shape[-3] if c.ndim == 4 else c.shape[0], -1) \
            if c.ndim != 2 else c
        ss = s.reshape(s.shape[-3] if s.ndim == 4 else s.shape[0], -1) \
            if s.ndim != 2 else s
        return apply_rotary(x, cc, ss)

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
        else:
            outs.append(apply_op("fused_rope", _rope, t, cos, sin))
    return tuple(outs)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      seed=None, name=None):
    """dropout(x) + y in one op (fusion/fused_dropout_add)."""
    from ...framework.random import rng_key
    if p == 0.0 or not training:
        return apply_op("fused_dropout_add", lambda a, b: a + b, x, y)
    key = rng_key()

    def _f(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        return jnp.where(keep, a / (1.0 - p), 0.0) + b
    return apply_op("fused_dropout_add", _f, x, y)


def swiglu(x, y=None, name=None):
    """silu(x) * y (kernels/swiglu_kernel.h); y=None splits x in half."""
    def _f(a, *rest):
        b = rest[0] if rest else None
        if b is None:
            a, b = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a) * b
    return apply_op("swiglu", _f, x) if y is None else \
        apply_op("swiglu", _f, x, y)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    """bias + activation (fusion/fused_bias_act)."""
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
            "swiglu": lambda a: jax.nn.silu(*jnp.split(a, 2, -1)[:1]) *
            jnp.split(a, 2, -1)[1], "identity": lambda a: a}
    fn = acts[act_method]

    def _f(a, *rest):
        if rest:
            a = a + rest[0]
        return fn(a)
    return apply_op("fused_bias_act", _f, x) if bias is None else \
        apply_op("fused_bias_act", _f, x, bias)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """matmul+bias (fused_gemm_epilogue)."""
    def _f(a, w, *rest):
        w = w.T if transpose_weight else w
        out = a @ w
        if rest:
            out = out + rest[0]
        return out
    return apply_op("fused_linear", _f, x, weight) if bias is None else \
        apply_op("fused_linear", _f, x, weight, bias)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    """gemm + bias + activation epilogue (fused_gemm_epilogue)."""
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "none": lambda a: a}

    def _f(a, w, b):
        a = a.T if trans_x else a
        w = w.T if trans_y else w
        return acts[activation](a @ w + b)
    return apply_op("fused_linear_activation", _f, x, y, bias)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with causal (upper-triangle) mask in one op
    (fused_softmax_mask_upper_triangle kernel)."""
    def _f(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -jnp.inf), axis=-1)
    return apply_op("softmax_mask_fuse_upper_triangle", _f, x)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """Pre-flash fused transformer attention block
    (fusion/fused_attention). qkv_weight: (3, H, D, hidden)."""
    from ...nn import functional as F

    def _f(a, qkvw, lw, *rest):
        rest = list(rest)
        qkvb = rest.pop(0) if qkv_bias is not None else None
        lb = rest.pop(0) if linear_bias is not None else None
        m = rest.pop(0) if attn_mask is not None else None
        lns = rest.pop(0) if ln_scale is not None else None
        lnb = rest.pop(0) if ln_bias is not None else None
        pls = rest.pop(0) if pre_ln_scale is not None else None
        plb = rest.pop(0) if pre_ln_bias is not None else None
        B, S, hidden = a.shape
        three, H, D, _ = qkvw.shape
        h = a
        if pre_layer_norm:
            mu = jnp.mean(a, -1, keepdims=True)
            var = jnp.var(a, -1, keepdims=True)
            a = (a - mu) * jax.lax.rsqrt(var + pre_ln_epsilon)
            if pls is not None:
                a = a * pls + plb
        qkv = jnp.einsum("bsx,thdx->tbshd", a, qkvw)   # (3, B, S, H, D)
        if qkvb is not None:
            qkv = qkv + qkvb[:, None, None]
        q, k, v = qkv[0], qkv[1], qkv[2]               # (B, S, H, D)
        scale = 1.0 / math.sqrt(D)
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if m is not None:
            sc = sc + m
        p = jax.nn.softmax(sc, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        out = out.reshape(B, S, H * D) @ lw
        if lb is not None:
            out = out + lb
        if add_residual:
            out = out + h
        if lns is not None and not pre_layer_norm:
            mu = jnp.mean(out, -1, keepdims=True)
            var = jnp.var(out, -1, keepdims=True)
            out = (out - mu) * jax.lax.rsqrt(var + ln_epsilon) * lns + lnb
        return out

    args = [x, qkv_weight, linear_weight]
    for t in (qkv_bias, linear_bias, attn_mask, ln_scale, ln_bias,
              pre_ln_scale, pre_ln_bias):
        if t is not None:
            args.append(t)
    return apply_op("fused_multi_head_attention", _f, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, add_residual=True,
                      name=None):
    """ffn block: ln -> linear -> act -> linear -> residual
    (fusion/fused_feedforward)."""
    acts = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}

    def _f(a, w1, w2, *rest):
        rest = list(rest)
        b1 = rest.pop(0) if linear1_bias is not None else None
        b2 = rest.pop(0) if linear2_bias is not None else None
        s1 = rest.pop(0) if ln1_scale is not None else None
        bb1 = rest.pop(0) if ln1_bias is not None else None
        s2 = rest.pop(0) if ln2_scale is not None else None
        bb2 = rest.pop(0) if ln2_bias is not None else None
        h = a
        if pre_layer_norm:
            mu = jnp.mean(a, -1, keepdims=True)
            var = jnp.var(a, -1, keepdims=True)
            a = (a - mu) * jax.lax.rsqrt(var + ln1_epsilon)
            if s1 is not None:
                a = a * s1 + bb1
        y = a @ w1
        if b1 is not None:
            y = y + b1
        y = acts[activation](y)
        y = y @ w2
        if b2 is not None:
            y = y + b2
        if add_residual:
            y = y + h
        if s2 is not None and not pre_layer_norm:
            mu = jnp.mean(y, -1, keepdims=True)
            var = jnp.var(y, -1, keepdims=True)
            y = (y - mu) * jax.lax.rsqrt(var + ln2_epsilon) * s2 + bb2
        return y

    args = [x, linear1_weight, linear2_weight]
    for t in (linear1_bias, linear2_bias, ln1_scale, ln1_bias, ln2_scale,
              ln2_bias):
        if t is not None:
            args.append(t)
    return apply_op("fused_feedforward", _f, *args)


def _apply_decode_rope(t, cos, sin, neox):
    """Rotary embedding for one decode step. neox=True rotates the two
    head-dim halves (rotate-half); neox=False rotates adjacent (even, odd)
    pairs — the reference kernel branches the same way on
    `neox_rotary_style` (masked_multihead_attention_kernel.cu:247) and
    models/llama.py uses the pair convention."""
    if neox:
        h1, h2 = jnp.split(t, 2, axis=-1)
        rot = jnp.concatenate([-h2, h1], axis=-1)
    else:
        even = t[..., 0::2]
        odd = t[..., 1::2]
        rot = jnp.stack([-odd, even], axis=-1).reshape(t.shape)
    return t * cos + rot * sin


def masked_multihead_attention(x, cache_kv, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1.0,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0, name=None):
    """Single-token decode attention over a contiguous KV cache.

    Parity: reference `masked_multihead_attention`
    (`phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu`, python
    `incubate/nn/functional/masked_multihead_attention.py`). Supported
    subset: fused qkv input x (B, (H + 2*KVH) * D), cache_kv
    (2, B, KVH, max_seq, D), sequence_lengths (B,) = number of cached
    tokens (the current token is appended at that position). Quant
    shift/smooth args are accepted for API parity but must be None.

    TPU-native: the cache append is one dynamic_update_slice and the
    attention a masked einsum — decode is HBM-bandwidth-bound and XLA
    already emits a single fused pass over the live cache; the paged
    Pallas kernel (block_multihead_attention) is the scalable path.
    Returns (out (B, H*D), updated cache_kv).
    """
    if any(a is not None for a in (qkv_out_scale, out_shift, out_smooth,
                                   beam_cache_offset)) \
            or out_scale > 0 or compute_dtype != "default":
        raise NotImplementedError(
            "quant/beam args of masked_multihead_attention not supported")

    def _f(xv, cache, *rest):
        rest = list(rest)
        lens = rest.pop(0) if sequence_lengths is not None else None
        rot = rest.pop(0) if rotary_tensor is not None else None
        mask = rest.pop(0) if src_mask is not None else None
        _, B, KVH, S, D = cache.shape
        H = xv.shape[1] // D - 2 * KVH
        q, knew, vnew = jnp.split(
            xv.reshape(B, H + 2 * KVH, D), [H, H + KVH], axis=1)
        if lens is None:
            lens = jnp.zeros((B,), jnp.int32)
        lens = lens.astype(jnp.int32).reshape(B)
        if rot is not None and rotary_emb_dims:
            # rot: (2, B, 1, S, D) cos/sin at absolute positions
            cos = jnp.take_along_axis(
                rot[0].reshape(B, S, D), lens[:, None, None], axis=1)
            sin = jnp.take_along_axis(
                rot[1].reshape(B, S, D), lens[:, None, None], axis=1)

            q = _apply_decode_rope(q, cos, sin, use_neox_rotary_style)
            knew = _apply_decode_rope(knew, cos, sin, use_neox_rotary_style)
        # append this step's K/V at position lens (per sequence)
        bidx = jnp.arange(B)
        kc = cache[0].at[bidx, :, lens].set(knew.astype(cache.dtype))
        vc = cache[1].at[bidx, :, lens].set(vnew.astype(cache.dtype))
        G = H // KVH
        qg = q.reshape(B, KVH, G, D).astype(jnp.float32)
        s = jnp.einsum("bhgd,bhsd->bhgs", qg, kc.astype(jnp.float32))
        s = s / math.sqrt(D)
        pos = jnp.arange(S)[None, None, None, :]
        s = jnp.where(pos <= lens[:, None, None, None], s, -1e30)
        if mask is not None:
            s = s + mask.reshape(B, 1, 1, -1)[..., :S]
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgs,bhsd->bhgd", p, vc.astype(jnp.float32))
        out = o.reshape(B, H * D).astype(xv.dtype)
        return out, jnp.stack([kc, vc], axis=0)

    args = [x, cache_kv]
    if sequence_lengths is not None:
        args.append(sequence_lengths)
    if rotary_tensor is not None:
        args.append(rotary_tensor)
    if src_mask is not None:
        args.append(src_mask)
    return apply_op("masked_multihead_attention", _f, *args)


def block_multihead_attention(qkv, k_cache, v_cache, seq_lens, block_tables,
                              num_heads=None, num_kv_heads=None,
                              rope_cos=None, rope_sin=None,
                              use_neox_rotary_style=False, name=None):
    """One decode step of attention over a PAGED KV cache.

    Parity: reference `block_multi_head_attention`
    (`phi/kernels/fusion/gpu/block_multi_head_attention.cu`, python
    `incubate/nn/functional/block_multihead_attention.py`) — the paged
    serving path. Supported subset: decode steps (one new token per
    sequence); prefill goes through `nn.functional.flash_attention` and
    `paged_cache_write` per position.

    qkv: (B, (H + 2*KVH) * D); k/v_cache: (num_pages, KVH, page_size, D)
    in the Pallas kernel's page-major layout
    (`kernels/paged_attention.py`); seq_lens (B,) = cached tokens before
    this step; block_tables (B, max_pages) int32.
    Returns (out (B, H*D), k_cache, v_cache).
    """
    from ...kernels.paged_attention import (paged_attention_decode,
                                            paged_cache_write)

    if (rope_cos is None) != (rope_sin is None):
        raise ValueError("rope_cos and rope_sin must be passed together")

    def _f(xv, kc, vc, lens, bt, *rest):
        rest = list(rest)
        cos = rest.pop(0) if rope_cos is not None else None
        sin = rest.pop(0) if rope_sin is not None else None
        _, KVH, _, D = kc.shape
        B = xv.shape[0]
        if num_kv_heads is not None and KVH != num_kv_heads:
            raise ValueError(
                f"cache has {KVH} kv heads, got num_kv_heads={num_kv_heads}")
        H = xv.shape[1] // D - 2 * KVH
        if num_heads is not None and H != num_heads:
            raise ValueError(f"qkv width implies {H} heads, got {num_heads}")
        q, knew, vnew = jnp.split(
            xv.reshape(B, H + 2 * KVH, D), [H, H + KVH], axis=1)
        if cos is not None:
            c = cos.reshape(B, 1, D)
            sn = sin.reshape(B, 1, D)
            q = _apply_decode_rope(q, c, sn, use_neox_rotary_style)
            knew = _apply_decode_rope(knew, c, sn, use_neox_rotary_style)
        lens = lens.astype(jnp.int32).reshape(B)
        kc, vc = paged_cache_write(kc, vc, knew, vnew, bt, lens)
        out = paged_attention_decode(q.reshape(B, H, D), kc, vc, bt,
                                     lens + 1)
        return out.reshape(B, H * D), kc, vc

    args = [qkv, k_cache, v_cache, seq_lens, block_tables]
    if rope_cos is not None:
        args += [rope_cos, rope_sin]
    return apply_op("block_multihead_attention", _f, *args)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Parity: incubate fused_matmul_bias (cublasLt epilogue kernel) —
    one taped matmul+bias op; XLA fuses the epilogue on TPU."""
    def _f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = [x, y] + ([bias] if bias is not None else [])
    return apply_op("fused_matmul_bias", _f, *args)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """y = layer_norm(residual + dropout(bias + x)) in one taped op
    (parity: fused_transformer.py:334)."""
    from ...framework import random as _random
    key = _random.default_rng().next_key() if (training and
                                               dropout_rate > 0) else None

    def _f(a, res, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        w = rest.pop(0) if ln_scale is not None else None
        lb = rest.pop(0) if ln_bias is not None else None
        if b is not None:
            a = a + b
        if key is not None:
            keep = jax.random.bernoulli(key, 1.0 - dropout_rate, a.shape)
            a = jnp.where(keep, a, 0.0)
            if mode == "upscale_in_train":
                a = a / (1.0 - dropout_rate)
        h = (res + a).astype(jnp.float32)
        mean = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        out = (h - mean) * jax.lax.rsqrt(var + ln_epsilon)
        out = out.astype(x_dtype)
        if w is not None:
            out = out * w
        if lb is not None:
            out = out + lb
        return out

    x_dtype = x.dtype
    args = [x, residual]
    for t in (bias, ln_scale, ln_bias):
        if t is not None:
            args.append(t)
    return apply_op("fused_bias_dropout_residual_layer_norm", _f, *args)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            rotary_emb_dims=0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, norm_type="layernorm",
                            use_neox_rotary_style=False, name=None, **kw):
    """Parity: incubate fused_multi_transformer (fused_multi_transformer_op
    — the whole pre-LN decoder stack as one op over per-layer weight
    lists). TPU-native: one taped op per layer; XLA fuses the chain. The
    qkv weight layout matches the reference: trans_qkvw=True means
    (3, H, D, hidden); activation in {gelu, relu, swiglu-ish geglu}.
    Supports self-attention training/prefill (causal); the serving decode
    path with paged caches lives in block_multihead_attention."""
    def _sdpa(q, k, v, causal, m):
        # (B, S, H, D) array-level causal attention
        sc = 1.0 / math.sqrt(q.shape[-1])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sc
        if causal:
            S_, K_ = s.shape[-2], s.shape[-1]
            tri = jnp.tril(jnp.ones((S_, K_), bool))
            s = jnp.where(tri, s, -1e9)
        if m is not None:
            s = s + m
        p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    num_layers = len(qkv_weights)
    attn_mask = getattr(attn_mask, "_data", attn_mask)
    out = x
    for i in range(num_layers):

        def _layer(a, lnw, lnb, qkvw, qkvb, lw, lb, flnw, flnb, f1w, f1b,
                   f2w, f2b):
            def norm(h, w, b):
                h32 = h.astype(jnp.float32)
                if norm_type == "rmsnorm":
                    var = jnp.mean(jnp.square(h32), -1, keepdims=True)
                    o = h32 * jax.lax.rsqrt(var + epsilon)
                else:
                    mean = jnp.mean(h32, -1, keepdims=True)
                    var = jnp.var(h32, -1, keepdims=True)
                    o = (h32 - mean) * jax.lax.rsqrt(var + epsilon)
                o = o.astype(h.dtype)
                if w is not None:
                    o = o * w
                if b is not None and norm_type != "rmsnorm":
                    o = o + b
                return o

            B, S, hidden = a.shape
            h = norm(a, lnw, lnb) if pre_layer_norm else a
            if trans_qkvw:
                nh, hd = qkvw.shape[1], qkvw.shape[2]
                wq = qkvw.reshape(3, nh * hd, hidden)
                qkv = jnp.einsum("bsh,tdh->btsd", h, wq)
            else:
                nh, hd = qkvw.shape[2], qkvw.shape[3]
                wq = qkvw.reshape(hidden, 3, nh * hd)
                qkv = jnp.einsum("bsh,htd->btsd", h, wq)
            if qkvb is not None:
                qkv = qkv + qkvb.reshape(3, 1, 1, nh * hd).transpose(
                    1, 0, 2, 3)
            q, k, v = [qkv[:, j].reshape(B, S, nh, hd) for j in range(3)]
            att = _sdpa(q, k, v, attn_mask is None, attn_mask)
            att = att.reshape(B, S, nh * hd)
            proj = att @ lw
            if lb is not None:
                proj = proj + lb
            a = a + proj                       # residual 1
            h = norm(a, flnw, flnb) if pre_layer_norm else a
            f = h @ f1w
            if f1b is not None:
                f = f + f1b
            if activation == "gelu":
                f = jax.nn.gelu(f)
            elif activation == "relu":
                f = jax.nn.relu(f)
            else:                               # geglu/swiglu pair layout
                g, u = jnp.split(f, 2, axis=-1)
                f = jax.nn.silu(g) * u
            f = f @ f2w
            if f2b is not None:
                f = f + f2b
            return a + f                       # residual 2

        def opt(seq, i=i):
            t = seq[i] if seq is not None and len(seq) > i else None
            return t

        args = [out, opt(ln_scales), opt(ln_biases), qkv_weights[i],
                opt(qkv_biases), linear_weights[i], opt(linear_biases),
                opt(ffn_ln_scales), opt(ffn_ln_biases), ffn1_weights[i],
                opt(ffn1_biases), ffn2_weights[i], opt(ffn2_biases)]
        out = apply_op("fused_multi_transformer", _layer, *args)
    return (out, cache_kvs) if cache_kvs is not None else out


def fused_moe(x, gate_weight, ffn1_weights, ffn2_weights, ffn1_biases=None,
              ffn2_biases=None, quant_method="None", moe_topk=2,
              norm_topk_prob=True, group_moe=False, name=None):
    """Parity: incubate fused_moe (fused_moe_kernel) — dense-compute MoE:
    softmax gate -> topk -> every expert runs, outputs combined by the
    (renormalized) gate weights. O(1) HLO ops via vmapped experts, the
    same design as distributed/moe.py; this surface takes stacked expert
    weights like the reference op."""
    def _f(a, gw, f1, f2, *rest):
        rest = list(rest)
        b1 = rest.pop(0) if ffn1_biases is not None else None
        b2 = rest.pop(0) if ffn2_biases is not None else None
        B, S, H = a.shape
        tok = a.reshape(B * S, H)
        logits = tok @ gw                                   # (T, E)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.maximum(
                topv.sum(-1, keepdims=True), 1e-9)

        def expert(w1, w2, bb1, bb2):
            h = tok @ w1
            if bb1 is not None:
                h = h + bb1
            h = jax.nn.gelu(h)
            o = h @ w2
            if bb2 is not None:
                o = o + bb2
            return o                                        # (T, H)

        outs = jax.vmap(expert)(
            f1, f2,
            b1 if b1 is not None else jnp.zeros((f1.shape[0], 1)),
            b2 if b2 is not None else jnp.zeros((f2.shape[0], 1)))
        # gather top-k expert outputs per token, weight, sum
        sel = jnp.take_along_axis(
            outs.transpose(1, 0, 2),                         # (T, E, H)
            topi[..., None].astype(jnp.int32), axis=1)       # (T, k, H)
        mixed = (sel * topv[..., None].astype(sel.dtype)).sum(1)
        return mixed.reshape(B, S, H)

    args = [x, gate_weight, ffn1_weights, ffn2_weights]
    if ffn1_biases is not None:
        args.append(ffn1_biases)
    if ffn2_biases is not None:
        args.append(ffn2_biases)
    return apply_op("fused_moe", _f, *args)


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """Parity: incubate variable_length_memory_efficient_attention
    (cutlass kernel) — (B, H, S, D) layout with per-sequence lengths;
    rides the varlen flash path / masked SDPA."""
    def _f(q, k, v, sl, kvl, *rest):
        m = rest[0] if mask is not None else None
        B, H, S, D = q.shape
        sc = scale if scale is not None else 1.0 / math.sqrt(D)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sc
        kpos = jnp.arange(k.shape[2])[None, None, None, :]
        valid = kpos < kvl[:, None, None, None]
        s = jnp.where(valid, s, -1e9)
        if causal:
            qpos = jnp.arange(S)[None, None, :, None]
            s = jnp.where(kpos <= qpos, s, -1e9)
        if m is not None:
            s = s + m
        p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    args = [query, key, value, seq_lens, kv_seq_lens]
    if mask is not None:
        args.append(mask)
    return apply_op("variable_length_memory_efficient_attention", _f, *args)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None):
    """Parity: incubate blha_get_max_len — max enc/dec lengths feeding
    block_multihead_attention's launch config."""
    def _f(enc, dec):
        return jnp.max(enc), jnp.max(dec)
    return apply_op("blha_get_max_len", _f, seq_lens_encoder,
                    seq_lens_decoder)


__all__ += ["fused_matmul_bias", "fused_bias_dropout_residual_layer_norm",
            "fused_multi_transformer", "fused_moe",
            "variable_length_memory_efficient_attention",
            "blha_get_max_len"]
