"""incubate.autograd — functional AD surface + prim toggles.

Parity: reference `python/paddle/incubate/autograd/` (Jacobian, Hessian,
jvp, vjp, forward_grad via the prim system). The functional transforms
live in paddle.autograd; the prim ops system collapses into jax's
program transforms (SURVEY A.7), so enable/disable_prim only record the
flag."""
from ..autograd import jacobian as Jacobian  # noqa: F401
from ..autograd import hessian as Hessian  # noqa: F401
from ..autograd import jvp, vjp  # noqa: F401

__all__ = ["Jacobian", "Hessian", "jvp", "vjp", "enable_prim",
           "disable_prim", "prim_enabled", "forward_grad", "grad"]

_prim = [False]


def enable_prim():
    _prim[0] = True


def disable_prim():
    _prim[0] = False


def prim_enabled():
    return _prim[0]


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode grad (parity: incubate.autograd.forward_grad): jvp of
    the identity program between inputs and outputs is not recoverable
    post-hoc in eager; use paddle.incubate.autograd.jvp on a function."""
    raise NotImplementedError(
        "forward_grad over captured programs requires the static prim "
        "pipeline; use incubate.autograd.jvp(func, xs) instead")


def grad(outputs, inputs, grad_outputs=None):
    from ..autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs)
