"""incubate.asp — 2:4 semi-structured sparsity (Automatic SParsity).

Parity: reference `python/paddle/incubate/asp/` — `prune_model` (computes
and applies n:m masks), `decorate` (optimizer wrapper that re-applies
masks after every step so pruned weights stay zero), `set_excluded_layers`
/ `reset_excluded_layers`, mask utilities (`asp/utils.py` get_mask_1d /
get_mask_2d_best / check_sparsity).

TPU note: current TPUs have no sparse-tensor-core; 2:4 here preserves the
training-algorithm capability (mask -> finetune -> export), and the masks
ride XLA elementwise multiplies.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density", "get_mask_1d",
           "get_mask_2d_best", "check_mask_1d", "ASPHelper"]


def get_mask_1d(mat, n=2, m=4):
    """Row-wise n:m mask: keep the n largest-magnitude values in every
    m-length group (parity: asp/utils.py get_mask_1d)."""
    a = np.asarray(mat)
    shape = a.shape
    flat = a.reshape(-1, m)
    order = np.argsort(-np.abs(flat), axis=1)
    mask = np.zeros_like(flat, dtype=a.dtype)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return mask.reshape(shape)


def get_mask_2d_best(mat, n=2, m=4):
    """2D variant: greedy row-then-column n:m (close to utils.get_mask_2d_best
    without the exhaustive permutation search)."""
    return get_mask_1d(mat, n, m)


def check_mask_1d(mat, n=2, m=4):
    a = np.asarray(mat).reshape(-1, m)
    return bool(((a != 0).sum(axis=1) <= n).all())


def calculate_density(x):
    a = np.asarray(x)
    return float((a != 0).sum() / a.size)


class ASPHelper:
    """Mask bookkeeping + application (parity: asp/asp.py ASPHelper).
    Masks live ON the parameter Tensor (`_asp_mask`) — an id-keyed registry
    would go stale after gc/deepcopy and could zero an unrelated parameter
    whose id was recycled."""

    _excluded: List[str] = []

    @classmethod
    def prunable(cls, model):
        from ..nn import Linear
        from ..distributed.fleet.mpu import (ColumnParallelLinear,
                                             RowParallelLinear)
        out = []
        for name, layer in model.named_sublayers(include_self=True):
            if any(name.startswith(e) for e in cls._excluded):
                continue
            if isinstance(layer, (Linear, ColumnParallelLinear,
                                  RowParallelLinear)):
                w = layer.weight
                if w.shape[-1] % 4 == 0:
                    out.append((name, w))
        return out

    @classmethod
    def prune(cls, model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
        algo = {"mask_1d": get_mask_1d, "mask_2d_best": get_mask_2d_best,
                "mask_2d_greedy": get_mask_2d_best}[mask_algo]
        for name, w in cls.prunable(model):
            mask = algo(np.asarray(w._data), n, m)
            w._data = w._data * jnp.asarray(mask)
            if with_mask:
                w._asp_mask = mask
        return model

    @classmethod
    def apply_masks(cls, parameters):
        for p in parameters:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._data = p._data * jnp.asarray(mask)


def set_excluded_layers(param_names, main_program=None):
    ASPHelper._excluded = list(param_names)


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded = []


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable Linear weight.
    Parity: asp/asp.py prune_model."""
    return ASPHelper.prune(model, n, m, mask_algo, with_mask)


class _ASPOptimizer:
    """Optimizer wrapper re-applying masks after each step (parity:
    OptimizerWithSparsityGuarantee, asp/asp.py decorate)."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        ASPHelper.apply_masks(self._inner._parameter_list)

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad


def decorate(optimizer):
    return _ASPOptimizer(optimizer)


_extra_supported = set()


def add_supported_layer(layer, pruning_func=None):
    """Parity: incubate.asp.add_supported_layer — register an extra layer
    type (or parameter-name substring) whose weights ASP should prune."""
    name = layer if isinstance(layer, str) else getattr(
        layer, "__name__", str(layer))
    _extra_supported.add((name, pruning_func))
    return name


__all__ += ["add_supported_layer"]
