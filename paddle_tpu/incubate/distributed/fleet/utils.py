"""incubate.distributed.fleet.utils — saved-program inspection helpers.

Parity: reference `incubate/distributed/fleet/utils.py` (__all__:
load_program, save_program, program_type_trans, check_saved_vars_try_dump,
parse_program, check_pruned_program_vars, graphviz) — debugging tools for
serialized inference programs. TPU-native mapping: a static `Program`
here is a placeholder registry whose op graph lives on the autograd tape
(static/__init__.py:49), so these tools serialize/inspect that
description: binary format = pickled dict, text format = JSON. The
reference's ProgramDesc-protobuf surgery (PS-era) is excluded per
SURVEY A.7; the entry points keep the same shapes so tooling scripts
port across.
"""
from __future__ import annotations

import json
import os
import pickle

__all__ = ["load_program", "save_program", "program_type_trans",
           "check_saved_vars_try_dump", "parse_program",
           "check_pruned_program_vars", "graphviz"]


def _describe(program):
    """A Program's serializable description: its placeholder variables
    (name, shape, dtype) — the persistable-var inventory the reference's
    tools walk."""
    out = []
    for t in getattr(program, "placeholders", []):
        d = getattr(t, "_data", None)
        out.append({
            "name": getattr(t, "name", None) or f"var_{id(t) & 0xffff}",
            "shape": list(getattr(d, "shape", ())),
            "dtype": str(getattr(d, "dtype", "")),
        })
    return {"vars": out}


def save_program(program, model_filename="__model__", is_text=False):
    """Parity: utils.py:82 — binary (pickle) or text (JSON) dump."""
    desc = _describe(program)
    if is_text:
        with open(model_filename, "w") as f:
            json.dump(desc, f, indent=2)
    else:
        with open(model_filename, "wb") as f:
            pickle.dump(desc, f)
    return model_filename


def load_program(model_filename, is_text=False):
    """Parity: utils.py:59 — returns the program description dict."""
    if is_text:
        with open(model_filename) as f:
            return json.load(f)
    with open(model_filename, "rb") as f:
        return pickle.load(f)


def program_type_trans(prog_dir, prog_fn, is_text):
    """Parity: utils.py:141 — convert a saved program between binary and
    text; returns the converted filename (reference convention:
    `<name>.bin` / `<name>.pbtxt` sibling)."""
    path = os.path.join(prog_dir, prog_fn)
    desc = load_program(path, is_text=is_text)
    if is_text:      # text -> binary
        out = prog_fn + ".bin"
        with open(os.path.join(prog_dir, out), "wb") as f:
            pickle.dump(desc, f)
    else:            # binary -> text
        out = prog_fn + ".pbtxt"
        with open(os.path.join(prog_dir, out), "w") as f:
            json.dump(desc, f, indent=2)
    return out


def parse_program(program, output_dir):
    """Parity: utils.py:454 — write a human-readable program report."""
    desc = program if isinstance(program, dict) else _describe(program)
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, "program.txt")
    with open(path, "w") as f:
        f.write(f"program: {len(desc['vars'])} vars\n")
        for v in desc["vars"]:
            f.write(f"  {v['name']}: shape={v['shape']} "
                    f"dtype={v['dtype']}\n")
    return path


def check_pruned_program_vars(train_prog, pruned_prog):
    """Parity: utils.py:91 — every pruned-program var must exist in the
    train program with matching shape/dtype; returns True on match and
    logs mismatches like the reference."""
    train = {v["name"]: v for v in _describe(train_prog)["vars"]}
    is_match = True
    for v in _describe(pruned_prog)["vars"]:
        tv = train.get(v["name"])
        if tv is None:
            print(f"var {v['name']} not in train program")
            is_match = False
        elif tv["shape"] != v["shape"] or tv["dtype"] != v["dtype"]:
            print(f"var {v['name']} shape/dtype mismatch: "
                  f"{tv['shape']}/{tv['dtype']} vs {v['shape']}/{v['dtype']}")
            is_match = False
    return is_match


def check_saved_vars_try_dump(dump_dir, dump_prog_fn, is_text_dump_program,
                              feed_config=None, fetch_config=None,
                              batch_size=1, save_filename=None):
    """Parity: utils.py:421 — load a saved program description and verify
    each declared var; returns the var list (the reference additionally
    replays a batch through the PS executor, excluded per A.7)."""
    desc = load_program(os.path.join(dump_dir, dump_prog_fn),
                        is_text=is_text_dump_program)
    missing = [v["name"] for v in desc["vars"] if not v["shape"]]
    if missing:
        print(f"vars with unknown shapes: {missing}")
    return desc["vars"]


def graphviz(block, output_dir="", filename="debug"):
    """Parity: utils.py:127 — emit a Graphviz .dot of the block's vars
    (the tape-resident op graph has no static description to plot; the
    placeholder inventory is what a Program owns here)."""
    desc = block if isinstance(block, dict) else _describe(block)
    os.makedirs(output_dir or ".", exist_ok=True)
    path = os.path.join(output_dir or ".", filename + ".dot")
    lines = ["digraph G {"]
    for v in desc["vars"]:
        lines.append(f'  "{v["name"]}" [shape=box, '
                     f'label="{v["name"]}\\n{v["shape"]}"];')
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path
