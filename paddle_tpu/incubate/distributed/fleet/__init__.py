"""incubate.distributed.fleet — PS-era fleet utilities (module-path
parity). The collective fleet lives at paddle.distributed.fleet; the
fleet_util/role-maker PS machinery is excluded per SURVEY A.7."""
from ....distributed.fleet import (  # noqa: F401
    init, distributed_model, distributed_optimizer, DistributedStrategy,
    UtilBase,
)
from ....distributed.fleet.utils import (  # noqa: F401
    recompute_sequential, recompute_hybrid,
)


class fleet_util:
    """Reference incubate fleet_util singleton surface (GPUPS/PSLIB);
    server-side ops raise, worker-side helpers ride UtilBase."""

    _util = UtilBase()

    @classmethod
    def __getattr__(cls, item):
        return getattr(cls._util, item)


from . import utils  # noqa: F401,E402

__all__ = ["init", "distributed_model", "distributed_optimizer",
           "DistributedStrategy", "UtilBase", "fleet_util",
           "recompute_sequential", "recompute_hybrid", "utils"]
