from . import io  # noqa: F401

__all__ = ["io"]
