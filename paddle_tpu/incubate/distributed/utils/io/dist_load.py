import pickle

__all__ = ["load"]


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
