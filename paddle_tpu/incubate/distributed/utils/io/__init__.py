"""incubate.distributed.utils.io — gathered/sharded state-dict IO.

Parity: reference incubate/distributed/utils/io/ (dist_save.py save,
dist_load.py load, save_for_auto.py save_for_auto_inference). The
sharded implementation is paddle.distributed.checkpoint (orbax); these
entry points add the gather-to-rank-0 convention."""
from . import dist_save  # noqa: F401
from . import save_for_auto  # noqa: F401
from .dist_save import save  # noqa: F401
from .dist_load import load  # noqa: F401
from .save_for_auto import save_for_auto_inference  # noqa: F401

__all__ = ["save", "load", "save_for_auto_inference", "dist_save",
           "save_for_auto"]
