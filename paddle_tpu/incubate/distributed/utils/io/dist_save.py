"""incubate dist_save: gather-then-save (reference dist_save.py save —
gathers sharded/TP state to one rank before serialization; the module
also re-exports save_for_auto_inference like the reference's
dist_save.py:30 import surface)."""
import numpy as np

from .save_for_auto import save_for_auto_inference  # noqa: F401

__all__ = ["save", "save_for_auto_inference"]


def save(state_dict, path, **configs):
    import pickle
    from .....core.tensor import Tensor
    host = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            host[k] = np.asarray(v._data)   # gathers across the mesh
        else:
            host[k] = v
    with open(path, "wb") as f:
        pickle.dump(host, f)
    return path
