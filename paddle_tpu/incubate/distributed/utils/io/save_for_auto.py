"""incubate save_for_auto: persist a dygraph dist model so the
auto-parallel loader can reshard it (reference save_for_auto.py).
Artifacts: one pickled host state dict + a JSON of per-parameter
placements."""
import json
import os

import numpy as np

__all__ = ["save_for_auto_inference"]


def save_for_auto_inference(path_prefix, dist_model, cvt2cpu=False):
    from .....core.tensor import Tensor
    net = getattr(dist_model, "network", dist_model)
    state = {}
    placements = {}
    for name, p in net.state_dict().items():
        state[name] = np.asarray(p._data)
        mesh = getattr(p, "process_mesh", None)
        pl = getattr(p, "placements", None)
        placements[name] = {
            "mesh_shape": list(getattr(mesh, "shape", []) or []),
            "placements": [str(x) for x in (pl or [])],
        }
    import pickle
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdparams", "wb") as f:
        pickle.dump(state, f)
    with open(path_prefix + ".dist_attr.json", "w") as f:
        json.dump(placements, f)
    return path_prefix
