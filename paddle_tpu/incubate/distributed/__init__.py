"""paddle.incubate.distributed — module-path parity (reference
incubate/distributed/): the live implementations are
paddle.distributed.*; the PS-era fleet_util surface raises."""
from . import fleet  # noqa: F401
from . import utils  # noqa: F401

__all__ = ["fleet", "utils"]
