"""PyLayer: user-defined forward/backward.

Parity: reference `python/paddle/autograd/py_layer.py` +
`paddle/fluid/eager/pylayer/`. The custom backward is attached to the tape
as a GradNode whose pullback calls the user's `backward` staticmethod.
"""
from __future__ import annotations

from typing import Any, List

import jax

from ..core import autograd
from ..core.autograd import GradNode
from ..core.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = [t.detach() if isinstance(t, Tensor) else t for t in tensors]

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff = [id(a) for a in args]

    def set_materialize_grads(self, value):
        self.materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs: List[Tensor] = [a for a in args if isinstance(a, Tensor)] + \
            [v for v in kwargs.values() if isinstance(v, Tensor)]
        need_grad = autograd.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        with autograd.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)
        if need_grad:
            out_tensors = [o for o in out_list if isinstance(o, Tensor)]
            avals = [jax.ShapeDtypeStruct(tuple(o._data.shape), o._data.dtype)
                     for o in out_tensors]

            def vjp_fn(cots):
                if not isinstance(cots, (list, tuple)):
                    cots = (cots,)
                cot_tensors = [Tensor(c) for c in cots]
                with autograd.no_grad():
                    grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                out = []
                gi = iter(grads)
                for t in tensor_inputs:
                    g = next(gi, None)
                    out.append(g._data if isinstance(g, Tensor) else g)
                return tuple(out)

            node = GradNode(cls.__name__, vjp_fn, tensor_inputs, avals,
                            out_treedef=None)
            for i, o in enumerate(out_tensors):
                fresh = Tensor(o._data, stop_gradient=False)
                fresh._grad_node = node
                fresh._grad_out_idx = i
                out_list[out_list.index(o)] = fresh
        return out_list[0] if single else tuple(out_list)
