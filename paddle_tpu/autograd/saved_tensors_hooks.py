"""Saved-tensor hooks (pack/unpack) — parity with
python/paddle/autograd/saved_tensors_hooks.py. On TPU the main use is
offload-style recompute; the tape currently saves tensors inside jax.vjp
residuals, so hooks apply to PyLayer ctx.save_for_backward paths."""
from __future__ import annotations

import threading

__all__ = ["saved_tensors_hooks"]


class _HookState(threading.local):
    def __init__(self):
        self.pack = None
        self.unpack = None


_state = _HookState()


def get_hooks():
    return _state.pack, _state.unpack


class saved_tensors_hooks:
    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._saved = (_state.pack, _state.unpack)
        _state.pack = self.pack_hook
        _state.unpack = self.unpack_hook
        return self

    def __exit__(self, *a):
        _state.pack, _state.unpack = self._saved
        return False
