"""paddle_tpu.autograd — public autograd API.

Parity: reference `python/paddle/autograd/` (backward, grad, PyLayer,
saved-tensor hooks, no_grad).
"""
from ..core.autograd import backward, grad, no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .saved_tensors_hooks import saved_tensors_hooks  # noqa: F401
from .functional import jacobian, hessian, jvp, vjp, Jacobian, Hessian  # noqa: F401

__all__ = ["jacobian", "hessian", "jvp", "vjp",
           "backward", "grad", "no_grad", "enable_grad", "PyLayer",
           "PyLayerContext", "saved_tensors_hooks"]
