"""Functional higher-order autograd: jacobian / hessian / jvp / vjp.

Parity: reference `python/paddle/autograd/autograd.py` (jacobian:461,
Hessian:193 — ys/xs tensors already connected through the tape,
batch_axis semantics) and `python/paddle/incubate/autograd/functional.py`
(jvp/vjp over a function).

TPU-native: jacobian rows come from tape backward passes
(grad(create_graph=...) composes to arbitrary order); jvp uses the
double-vjp trick over the same tape, so no separate forward-mode
machinery is needed.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.autograd import grad as _grad
from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["jacobian", "hessian", "jvp", "vjp", "Jacobian", "Hessian"]


def _tensors(xs):
    return [xs] if isinstance(xs, Tensor) else list(xs)


def _flat_numel(t, batch_axis):
    shape = list(t.shape)
    if batch_axis is not None:
        shape.pop(batch_axis)
    return int(np.prod(shape)) if shape else 1


def jacobian(ys, xs, batch_axis=None):
    """d ys / d xs through the tape connecting them.

    batch_axis=None: single Jacobian (M, N) per (y, x) pair;
    batch_axis=0: (B, M, N) with per-sample rows. Returns a Tensor when
    both ys and xs are single Tensors, else nested tuples (reference
    autograd.py:461 contract, evaluated eagerly)."""
    ys_l, xs_l = _tensors(ys), _tensors(xs)
    if batch_axis not in (None, 0):
        raise ValueError("batch_axis must be None or 0")

    rows_per_y = []
    for y in ys_l:
        m = _flat_numel(y, batch_axis)
        grads_rows = [[] for _ in xs_l]
        for i in range(m):
            # seed one cotangent basis vector (per batch element when
            # batch_axis=0 — handled by seeding the whole batch column)
            ydt = y._data.dtype
            if batch_axis is None:
                seed = jnp.zeros(int(np.prod(y.shape)) if y.shape else 1,
                                 ydt)
                seed = seed.at[i].set(1.0).reshape(tuple(y.shape))
            else:
                B = y.shape[0]
                rest = int(np.prod(y.shape[1:])) if y.shape[1:] else 1
                seed = jnp.zeros((B, rest), ydt).at[:, i].set(1.0)
                seed = seed.reshape(tuple(y.shape))
            gs = _grad([y], xs_l, grad_outputs=[Tensor(seed)],
                       retain_graph=True, allow_unused=True)
            for j, g in enumerate(gs):
                if g is None:
                    z = jnp.zeros(tuple(xs_l[j].shape),
                                  xs_l[j]._data.dtype)
                    g = Tensor(z)
                grads_rows[j].append(g)
        per_x = []
        for j, rows in enumerate(grads_rows):
            n = _flat_numel(xs_l[j], batch_axis)

            def _stack(*rs):
                if batch_axis is None:
                    return jnp.stack([r.reshape(-1) for r in rs], 0)
                B = rs[0].shape[0]
                return jnp.stack([r.reshape(B, -1) for r in rs], 1)
            per_x.append(apply_op("jacobian_stack", _stack, *rows))
        rows_per_y.append(tuple(per_x))

    if isinstance(ys, Tensor) and isinstance(xs, Tensor):
        return rows_per_y[0][0]
    if isinstance(ys, Tensor):
        return rows_per_y[0]
    if isinstance(xs, Tensor):
        return tuple(r[0] for r in rows_per_y)
    return tuple(rows_per_y)


# reference exposes Jacobian/Hessian lazy classes; eager Tensors satisfy
# the same indexing surface
Jacobian = jacobian
Hessian = None  # assigned below


def hessian(ys, xs, batch_axis=None):
    """d2 ys / d xs2 for scalar (or per-sample scalar) ys. Computed as
    rows of grad-of-grad (create_graph on the first backward)."""
    xs_l = _tensors(xs)
    if batch_axis not in (None, 0):
        raise ValueError("batch_axis must be None or 0")
    seed = Tensor(jnp.ones(tuple(ys.shape), ys._data.dtype))
    first = _grad([ys], xs_l, grad_outputs=[seed], create_graph=True,
                  allow_unused=False)
    out = []
    for j, g in enumerate(first):
        out.append(jacobian(g, xs_l[j], batch_axis=batch_axis))
    if isinstance(xs, Tensor):
        return out[0]
    return tuple(out)


Hessian = hessian


def vjp(func, xs, v=None):
    """(outputs, vjp_result): pull back cotangents v through func.
    Parity: incubate/autograd/functional.py vjp."""
    xs_l = _tensors(xs)
    for t in xs_l:
        t.stop_gradient = False
    ys = func(*xs_l)
    ys_l = _tensors(ys)
    if v is None:
        # reference contract: v=None means all-ones cotangents
        v_l = [Tensor(jnp.ones(tuple(y.shape), y._data.dtype))
               for y in ys_l]
    else:
        v_l = _tensors(v)
    gs = _grad(ys_l, xs_l, grad_outputs=v_l, retain_graph=True,
               allow_unused=True)
    gs = gs[0] if isinstance(xs, Tensor) else tuple(gs)
    return ys, gs


def jvp(func, xs, v=None):
    """(outputs, jvp_result): push forward tangents v through func via the
    double-vjp trick (vjp of the vjp — no forward-mode tape needed)."""
    xs_l = _tensors(xs)
    for t in xs_l:
        t.stop_gradient = False
    ys = func(*xs_l)
    ys_l = _tensors(ys)
    if v is None:
        v_l = [Tensor(jnp.ones(tuple(t.shape), t._data.dtype))
               for t in xs_l]
    else:
        v_l = _tensors(v)
    # u: dummy cotangents (differentiated through)
    u = [Tensor(jnp.zeros(tuple(y.shape), y._data.dtype),
                stop_gradient=False) for y in ys_l]
    g = _grad(ys_l, xs_l, grad_outputs=u, create_graph=True,
              allow_unused=True)
    g = [gi if gi is not None else
         Tensor(jnp.zeros(tuple(x.shape), x._data.dtype),
                stop_gradient=False)
         for gi, x in zip(g, xs_l)]
    jv = _grad(g, u, grad_outputs=v_l, retain_graph=True,
               allow_unused=True)
    jv = [ji if ji is not None else
          Tensor(jnp.zeros(tuple(y.shape), y._data.dtype))
          for ji, y in zip(jv, ys_l)]
    jv = jv[0] if isinstance(ys, Tensor) else tuple(jv)
    return ys, jv
