"""Graph/geometric ops.

Parity: reference `python/paddle/geometric/` — message passing
send_u_recv / send_ue_recv / send_uv (`geometric/message_passing/send_recv.py`),
segment_{sum,mean,max,min} (`geometric/math.py` via phi segment kernels).

TPU-native: all of these are jax.ops.segment_* reductions — XLA lowers to
sorted-scatter which stays on-device; no atomics needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
    "weighted_sample_neighbors",
    "send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed from sum/count
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment(name, reduce_op, data, ids, num_segments):
    def _f(d, i):
        n = num_segments
        if reduce_op == "mean":
            s = jax.ops.segment_sum(d, i, n)
            c = jax.ops.segment_sum(jnp.ones_like(i, d.dtype), i, n)
            return s / jnp.maximum(c, 1).reshape(
                (-1,) + (1,) * (d.ndim - 1))
        out = _REDUCERS[reduce_op](d, i, n)
        if reduce_op in ("max", "min"):
            # empty segments come back +-inf; reference returns 0. Detect
            # emptiness via the segment count — an isfinite() test would
            # also clobber legitimate +-inf data values.
            c = jax.ops.segment_sum(jnp.ones_like(i, jnp.int32), i, n)
            empty = (c == 0).reshape((-1,) + (1,) * (d.ndim - 1))
            return jnp.where(empty, jnp.zeros_like(out), out)
        return out
    return apply_op(name, _f, data, ids)


def segment_sum(data, segment_ids, name=None, num_segments=None):
    n = num_segments if num_segments is not None \
        else int(jnp.max(segment_ids._data)) + 1
    return _segment("segment_sum", "sum", data, segment_ids, n)


def segment_mean(data, segment_ids, name=None, num_segments=None):
    n = num_segments if num_segments is not None \
        else int(jnp.max(segment_ids._data)) + 1
    return _segment("segment_mean", "mean", data, segment_ids, n)


def segment_max(data, segment_ids, name=None, num_segments=None):
    n = num_segments if num_segments is not None \
        else int(jnp.max(segment_ids._data)) + 1
    return _segment("segment_max", "max", data, segment_ids, n)


def segment_min(data, segment_ids, name=None, num_segments=None):
    n = num_segments if num_segments is not None \
        else int(jnp.max(segment_ids._data)) + 1
    return _segment("segment_min", "min", data, segment_ids, n)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and segment-reduce onto dst.
    Parity: paddle.geometric.send_u_recv."""
    n = out_size or x.shape[0]

    def _f(xa, s, d):
        msgs = xa[s]
        if reduce_op == "mean":
            ssum = jax.ops.segment_sum(msgs, d, n)
            c = jax.ops.segment_sum(jnp.ones_like(d, xa.dtype), d, n)
            return ssum / jnp.maximum(c, 1).reshape(
                (-1,) + (1,) * (xa.ndim - 1))
        out = _REDUCERS[reduce_op](msgs, d, n)
        if reduce_op in ("max", "min"):
            # empty destinations -> 0 (count-based; isfinite would clobber
            # legitimate +-inf messages)
            c = jax.ops.segment_sum(jnp.ones_like(d, jnp.int32), d, n)
            empty = (c == 0).reshape((-1,) + (1,) * (out.ndim - 1))
            return jnp.where(empty, jnp.zeros_like(out), out)
        return out
    return apply_op("send_u_recv", _f, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, reduce onto dst.
    Parity: paddle.geometric.send_ue_recv."""
    n = out_size or x.shape[0]
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def _f(xa, ya, s, d):
        msgs = combine(xa[s], ya)
        if reduce_op == "mean":
            ssum = jax.ops.segment_sum(msgs, d, n)
            c = jax.ops.segment_sum(jnp.ones_like(d, xa.dtype), d, n)
            return ssum / jnp.maximum(c, 1).reshape(
                (-1,) + (1,) * (msgs.ndim - 1))
        out = _REDUCERS[reduce_op](msgs, d, n)
        if reduce_op in ("max", "min"):
            # empty destinations -> 0 (count-based; isfinite would clobber
            # legitimate +-inf messages)
            c = jax.ops.segment_sum(jnp.ones_like(d, jnp.int32), d, n)
            empty = (c == 0).reshape((-1,) + (1,) * (out.ndim - 1))
            return jnp.where(empty, jnp.zeros_like(out), out)
        return out
    return apply_op("send_ue_recv", _f, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] (op) y[dst]. Parity: paddle.geometric.send_uv."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def _f(xa, ya, s, d):
        return combine(xa[s], ya[d])
    return apply_op("send_uv", _f, x, y, src_index, dst_index)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Parity: paddle.geometric.reindex_graph — same contract as the
    incubate implementation."""
    from ..incubate import graph_reindex
    return graph_reindex(x, neighbors, count, value_buffer, index_buffer)


def reindex_heter_graph(x, neighbors_list, count_list, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous reindex: one shared node table across edge types,
    CONCATENATED outputs (reference geometric/reindex.py:153 returns flat
    reindex_src / reindex_dst / out_nodes tensors)."""
    import numpy as np
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..incubate import graph_reindex
    all_nb, all_ct = [], []
    for neighbors, count in zip(neighbors_list, count_list):
        all_nb.append(np.asarray(
            neighbors._data if hasattr(neighbors, "_data")
            else neighbors).reshape(-1))
        all_ct.append(np.asarray(
            count._data if hasattr(count, "_data") else count).reshape(-1))
    nb = Tensor(jnp.asarray(np.concatenate(all_nb).astype(np.int64)))
    ct = Tensor(jnp.asarray(np.concatenate(all_ct).astype(np.int64)))
    return graph_reindex(x, nb, ct)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Parity: paddle.geometric.sample_neighbors."""
    from ..incubate import graph_sample_neighbors
    return graph_sample_neighbors(row, colptr, input_nodes, eids=eids,
                                  sample_size=sample_size,
                                  return_eids=return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional neighbor sampling (parity:
    geometric.weighted_sample_neighbors) — delegates to the incubate
    sampler with edge_weight set."""
    from ..incubate import graph_sample_neighbors
    return graph_sample_neighbors(row, colptr, input_nodes, eids=eids,
                                  sample_size=sample_size,
                                  return_eids=return_eids,
                                  edge_weight=edge_weight)
