"""Optimizer base + the standard zoo.

Parity: reference `python/paddle/optimizer/optimizer.py:127` (Optimizer base:
regularization, grad clip, LR scheduling, accumulators) and the phi optimizer
kernels (sgd/momentum/adam/adamw/lamb...). Updates are jnp expressions, so a
whole `opt.step()` traces into the fused train step under to_static — the
analog of the reference's fused_adam multi-tensor kernels is XLA fusing the
update across parameters.

Master weights: with multi_precision=True (or AMP O2), accumulators and the
update run in fp32 while the parameter stays bf16/fp16
(reference: fleet/utils/mix_precision_utils.py + master_weight in adamw).

bf16 optimizer states (TPU-native extension): `moment_dtype="bfloat16"`
(or FLAGS_bf16_optimizer_states=1 as the global default) STORES every
accumulator in bf16 while the update math still runs in fp32 (upcast on
read, downcast on store; master weights stay fp32). The AdamW update is
HBM-bound at the roofline (measured ~21 ms for 608M fp32 states,
RELAY_STATUS.md r4), so halving the moment bytes is the one remaining
flagship-MFU lever. Reference analog: the low-precision moments path of
fused_adam / PaddleNLP's bf16 optimizer
(paddle/phi/kernels/fusion/gpu/fused_adam_kernel.cu uses MT=fp32 compute
over narrow stored moments the same way).

Fused update (ISSUE 9): `AdamW(..., fused=True)` (or
FLAGS_fused_optimizer=1 as the global default) packs every eligible
parameter leaf into padded flat buckets (kernels/fused_optimizer.py —
one (rows, 128) bucket per (param dtype, effective-lr, decay-on)
group) and performs the whole AdamW update in ONE Pallas pass: one
read and one write per state byte instead of XLA's per-leaf
upcast/downcast round trips. Moments and fp32 master weights then LIVE
in bucket form (accumulator slots "fused_m"/"fused_v"/"fused_master"
keyed by bucket id — raw_state round-trips them through the to_static
donated-buffer step unchanged), while `state_dict()` de-bucketizes to
the canonical per-parameter `moment1_i`/`moment2_i`/`master_i` keys so
checkpoints stay interchangeable with the unfused optimizer (and
`set_state_dict` re-buckets lazily at the next step). Eligibility:
fp32 parameters, or narrow parameters under multi_precision=True (a
narrow parameter WITHOUT a master weight keeps the eager per-leaf
path — fused compute is fp32 by contract and would silently change
its numerics); amsgrad keeps the eager path too. Non-fused optimizers
(SGD/Lamb/LBFGS/...) ignore the flag entirely.

ZeRO-1 (same bucket layout): when the active fleet mesh has
sharding_degree > 1, the fused path shards the moment and master
buckets over the 'sharding' axis (GSPMD constraints, no shard_map) —
each rank updates rows/degree of optimizer state and the replication
constraint on the param bucket is the parameter all-gather. Per-chip
optimizer-state bytes drop by the sharding degree; see BASELINE.md for
the sizing math.

Grad clip x narrow states: grad clip runs BEFORE any accumulator is
touched, on fp32 upcasts of the raw gradients (nn/clip.py), so the
clip scale is identical whatever `moment_dtype` or `fused` say —
moments narrow only at storage, and with multi_precision=False the
fp32 parameter IS the master value the clipped update applies to.
tests/test_fused_optimizer.py pins both properties.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from .. import profiler as _profiler
from ..profiler import monitor as _monitor
from ..profiler.monitor import grad_global_norm
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "Adadelta", "Adamax", "RMSProp", "Lamb", "NAdam", "RAdam", "ASGD",
           "Rprop", "LBFGS"]


def _register_moment_flag():
    from ..utils.flags import define_flag
    define_flag("bf16_optimizer_states", False,
                "store optimizer accumulators in bfloat16 (fp32 compute)")
    define_flag("fused_optimizer", False,
                "use the fused multi-tensor Pallas update for optimizers "
                "that support it (AdamW)")


_register_moment_flag()

# accumulator slots that hold BUCKETED fused state (kernels/
# fused_optimizer.py layouts) rather than per-parameter arrays;
# state_dict() de-bucketizes them, raw_state() passes them through
_FUSED_SLOTS = ("fused_m", "fused_v", "fused_master")


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False,
                 moment_dtype=None, fused=None):
        if parameters is None:
            raise ValueError(
                "paddle_tpu optimizers require an explicit parameter list "
                "(pass model.parameters()).")
        self._parameter_list = list(parameters)
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:
            self._weight_decay = weight_decay  # None or regularizer-like
        # accumulators: slot name -> param index -> array
        self._accumulators: Dict[str, Dict[int, jax.Array]] = {}
        self._master_weights: Dict[int, jax.Array] = {}
        self._step_count = 0
        if moment_dtype is None:
            from ..utils.flags import flags
            if flags("bf16_optimizer_states"):
                moment_dtype = "bfloat16"
        self._moment_dtype = jnp.dtype(moment_dtype) \
            if moment_dtype is not None else None
        if fused is None:
            from ..utils.flags import flags
            fused = bool(flags("fused_optimizer"))
        # only optimizers that implement _fused_step (AdamW) ever act on
        # this; for the rest the flag is inert by construction
        self._fused = bool(fused)
        # bucket bookkeeping (fused path): group key -> {uid, layout,
        # sig, ...}; geometry is rebuilt deterministically from the
        # parameter list, only the ARRAYS live in _accumulators
        self._fused_buckets: Dict = {}

    # ------------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ----------------------------------------------------------- accumulators
    def _acc(self, name: str, idx: int, like: jax.Array, fill=0.0) -> jax.Array:
        """Accumulator READ: with moment_dtype set, storage is narrow but
        the returned view is upcast to fp32 so every optimizer's update
        math runs full-precision unchanged (XLA fuses the converts into
        the update, so the HBM traffic is the narrow array)."""
        slot = self._accumulators.setdefault(name, {})
        if idx not in slot:
            dtype = self._moment_dtype if self._moment_dtype is not None \
                else (jnp.float32 if self._multi_precision else like.dtype)
            slot[idx] = jnp.full(like.shape, fill, dtype)
        a = slot[idx]
        if self._moment_dtype is not None and a.dtype == self._moment_dtype:
            return a.astype(jnp.float32)
        return a

    def _set_acc(self, name: str, idx: int, value):
        if self._moment_dtype is not None:
            value = value.astype(self._moment_dtype)
        self._accumulators[name][idx] = value

    def _master(self, idx: int, p: Tensor) -> jax.Array:
        if not self._multi_precision or p.dtype == jnp.float32:
            return p._data
        if idx not in self._master_weights:
            self._master_weights[idx] = p._data.astype(jnp.float32)
        return self._master_weights[idx]

    def _writeback(self, idx: int, p: Tensor, new_master):
        if self._multi_precision and p.dtype != jnp.float32:
            self._master_weights[idx] = new_master
            p._data = new_master.astype(p.dtype)
        else:
            p._data = new_master

    # ------------------------------------------------------------------ step
    @autograd.no_grad
    def step(self):
        # profiler span (ISSUE 11): optimizer time shows on the host
        # timeline next to dispatch op spans — one attribute check when
        # no Profiler records (the ops.dispatch pattern)
        if _profiler._tracer.enabled:
            with _profiler.RecordEvent(
                    "optimizer.step", _profiler.TracerEventType.Optimization):
                return self._step_impl()
        return self._step_impl()

    minimize_step = step

    def _step_impl(self):
        params_grads = []
        for p in self._parameter_list:
            if p.stop_gradient or p._grad_buffer is None:
                continue
            params_grads.append((p, Tensor(p._grad_buffer)))
        # TrainingMonitor hook (ISSUE 11): the PRE-clip gradient global
        # norm + lr, stashed lazily for the monitor's next step() fetch.
        # With no monitor attached this is ONE module-global truthiness
        # check — asserted allocation-free by the booby-trap test. Under
        # a to_static trace grads are tracers and grad_global_norm
        # returns None (the python-side hook must not leak tracers).
        if _monitor._ACTIVE:
            mon = _monitor._ACTIVE[-1]
            gn = grad_global_norm(self._parameter_list) \
                if mon.track_grad_norm else None
            mon.note(lr=self.get_lr(), grad_norm=gn)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._step_count += 1
        if self._fused and params_grads:
            # returns the (p, g) pairs the fused path did NOT handle;
            # base implementation handles nothing (flag inert for
            # optimizers without a fused update)
            if _profiler._tracer.enabled:
                with _profiler.RecordEvent(
                        "optimizer.fused_step",
                        _profiler.TracerEventType.Optimization):
                    params_grads = self._fused_step(params_grads, lr)
            else:
                params_grads = self._fused_step(params_grads, lr)
        for idx, p in enumerate(self._parameter_list):
            match = next((g for (pp, g) in params_grads if pp is p), None)
            if match is None:
                continue
            g = match._data
            lr_scale = getattr(p, "_lr_scale", 1.0)
            self._apply_one(idx, p, g, lr * lr_scale)

    def _fused_step(self, params_grads, lr):
        """Fused multi-tensor hook: handle what you can, return the
        rest for the per-parameter loop. Base: nothing is handled."""
        return params_grads

    # ----------------------------------------------- fused bucket plumbing
    @staticmethod
    def _fused_mesh():
        """(mesh, degree) of the active 'sharding' axis, or (None, 1) —
        degree > 1 turns the fused update into ZeRO-1."""
        try:
            from ..distributed.fleet import fleet as fleet_mod
            mesh = getattr(getattr(fleet_mod, "_hcg", None), "mesh", None)
        except Exception:
            mesh = None
        if mesh is None:
            return None, 1
        degree = dict(mesh.shape).get("sharding", 1)
        return (mesh, degree) if degree > 1 else (None, 1)

    def _fused_state_entries(self):
        """Per-parameter view of every bucketed slot (for state_dict):
        {canonical_key: array} by slicing the live buckets."""
        from ..kernels.fused_optimizer import unpack_bucket
        out = {}
        for rec in self._fused_buckets.values():
            uid, layout = rec["uid"], rec["layout"]
            for slot, canon in (("fused_m", "moment1"),
                                ("fused_v", "moment2"),
                                ("fused_master", "master")):
                bucket = self._accumulators.get(slot, {}).get(uid)
                if bucket is None:
                    continue
                for arr, (idx, _, _, _) in zip(
                        unpack_bucket(bucket, layout), layout.entries):
                    out[f"{canon}_{idx}"] = arr
        return out

    def _drop_fused_buckets(self, debucketize=False):
        """Forget bucketed storage — optionally writing it back to the
        canonical per-parameter slots first (layout-change path)."""
        if debucketize:
            for key, arr in self._fused_state_entries().items():
                name, idx = key.rsplit("_", 1)
                if name == "master":
                    self._master_weights[int(idx)] = arr
                else:
                    self._accumulators.setdefault(name, {})[int(idx)] = arr
        for slot in _FUSED_SLOTS:
            self._accumulators.pop(slot, None)
        self._fused_buckets.clear()

    def _apply_one(self, idx: int, p: Tensor, g: jax.Array, lr: float):
        raise NotImplementedError

    def _decayed_grad(self, p, g):
        """L2-regularizer-style decay (coupled; AdamW overrides w/ decoupled).
        Accepts paddle.regularizer objects (L1Decay adds coeff*sign(w))."""
        wd = self._weight_decay
        if isinstance(wd, float) and wd != 0.0:
            return g + wd * p._data.astype(g.dtype)
        if wd is not None and hasattr(wd, "apply"):
            return wd.apply(p._data, g)
        return g

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        """backward + apply. Matches the reference contract
        (python/paddle/optimizer/optimizer.py Optimizer.minimize): does
        NOT clear gradients — p.grad stays inspectable afterwards, the
        caller owns clear_grad() — and returns (optimize_ops,
        params_grads); optimize_ops is [] in dygraph."""
        loss.backward()
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.grad is not None]
        self.step()
        return [], params_grads

    # --------------------------------------------------------------- state IO
    def state_dict(self):
        out = {}
        for name, slot in self._accumulators.items():
            if name in _FUSED_SLOTS:
                continue    # exported in canonical per-parameter form below
            for idx, arr in slot.items():
                out[f"{name}_{idx}"] = Tensor(arr)
        for idx, arr in self._master_weights.items():
            out[f"master_{idx}"] = Tensor(arr)
        # bucketed fused state de-bucketizes to the same canonical keys
        # the unfused optimizer writes, so checkpoints are
        # interchangeable across fused=True/False
        for key, arr in self._fused_state_entries().items():
            out[key] = Tensor(arr)
        out["@step"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        # canonical per-parameter entries rule: stale buckets would
        # shadow them at the next fused step, so DEBUCKETIZE into the
        # canonical slots first (a PARTIAL state dict must overwrite
        # only the keys it carries, same as the unfused path — dropping
        # the buckets outright would silently zero the rest), then let
        # the incoming entries overwrite; the fused path re-buckets
        # from the canonical slots lazily at the next step
        self._drop_fused_buckets(debucketize=True)
        for key, v in state.items():
            if key == "@step":
                self._step_count = int(v)
            elif key == "LR_Scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(v)
            elif key.startswith("master_"):
                self._master_weights[int(key[7:])] = \
                    v._data if isinstance(v, Tensor) else jnp.asarray(v)
            else:
                name, idx = key.rsplit("_", 1)
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                self._accumulators.setdefault(name, {})[int(idx)] = arr
        return self

    # ------------------------------------------- functional-state (jit bridge)
    def raw_state(self):
        st = {f"{n}_{i}": a for n, slot in self._accumulators.items()
              for i, a in slot.items()}
        st.update({f"master_{i}": a for i, a in self._master_weights.items()})
        return st

    def load_raw_state(self, raw):
        for key, arr in raw.items():
            if key.startswith("master_"):
                self._master_weights[int(key[7:])] = arr
            else:
                name, idx = key.rsplit("_", 1)
                self._accumulators.setdefault(name, {})[int(idx)] = arr


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m = self._master(idx, p)
        self._writeback(idx, p, m - lr * g.astype(m.dtype))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m = self._master(idx, p)
        g = g.astype(m.dtype)
        vel = self._acc("velocity", idx, m)
        vel = self._momentum * vel + g
        self._set_acc("velocity", idx, vel)
        if self._nesterov:
            update = g + self._momentum * vel
        else:
            update = vel
        self._writeback(idx, p, m - lr * update)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None, amsgrad=False,
                 moment_dtype=None, fused=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision, moment_dtype=moment_dtype,
                         fused=fused)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        m = self._acc("moment1", idx, m_w)
        v = self._acc("moment2", idx, m_w)
        t = self._step_count
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", idx, m)
        self._set_acc("moment2", idx, v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        if self._amsgrad:
            vmax = self._acc("moment2_max", idx, m_w)
            vmax = jnp.maximum(vmax, vhat)
            self._set_acc("moment2_max", idx, vmax)
            vhat = vmax
        self._writeback(idx, p, m_w - lr * mhat / (jnp.sqrt(vhat) + self._eps))


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 amsgrad=False, moment_dtype=None, fused=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         name=name, amsgrad=amsgrad,
                         moment_dtype=moment_dtype, fused=fused)
        from ..regularizer import L1Decay, L2Decay
        if isinstance(weight_decay, L1Decay):
            # parity: reference AdamW rejects regularizer objects — a
            # silent float() would turn L1 into decoupled L2 decay
            raise TypeError(
                "AdamW applies decoupled L2 decay; L1Decay is not "
                "supported (use Adam with weight_decay=L1Decay(...))")
        if isinstance(weight_decay, L2Decay):
            weight_decay = weight_decay.coeff
        self._wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_fn = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _apply_one(self, idx, p, g, lr):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        m_w = self._master(idx, p)
        if self._wd != 0.0 and (self._apply_decay_fn is None or
                                self._apply_decay_fn(p.name or f"param_{idx}")):
            m_w = m_w * (1.0 - lr * self._wd)
        g = g.astype(m_w.dtype)
        m = self._acc("moment1", idx, m_w)
        v = self._acc("moment2", idx, m_w)
        t = self._step_count
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", idx, m)
        self._set_acc("moment2", idx, v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        if self._amsgrad:
            vmax = self._acc("moment2_max", idx, m_w)
            vmax = jnp.maximum(vmax, vhat)
            self._set_acc("moment2_max", idx, vmax)
            vhat = vmax
        self._writeback(idx, p, m_w - lr * mhat / (jnp.sqrt(vhat) + self._eps))

    # ------------------------------------------------------- fused update
    def _fused_eligible(self, p) -> bool:
        """Fused compute is fp32 by contract: fp32 parameters, or
        narrow parameters whose fp32 truth is a master weight. A narrow
        parameter WITHOUT a master runs its eager bf16/fp16 update
        unchanged (fusing it would silently improve its numerics)."""
        if p._data.dtype == jnp.float32:
            return True
        return self._multi_precision and \
            p._data.dtype in (jnp.bfloat16, jnp.float16)

    def _fused_step(self, params_grads, lr):
        """Bucketed multi-tensor AdamW (kernels/fused_optimizer.py).

        Groups eligible parameters by (dtype, effective-lr, decay-on),
        packs each group into one padded (rows, 128) bucket, and runs
        the whole update in one Pallas pass (one read + one write per
        state byte). Moments/master weights persist IN bucket form
        under the "fused_m"/"fused_v"/"fused_master" accumulator slots;
        with an active 'sharding' mesh axis the update runs ZeRO-1
        sharded. Returns the pairs the fused path does not cover
        (narrow params without master, amsgrad)."""
        if self._amsgrad:
            return params_grads
        from ..kernels.fused_optimizer import (
            adamw_scalars, build_bucket_layout, fused_adamw_bucket,
            fused_adamw_zero1, pack_bucket, unpack_bucket)

        mesh, degree = self._fused_mesh()
        idx_of = {id(p): i for i, p in enumerate(self._parameter_list)}
        groups: Dict = {}
        leftover = []
        for p, g in params_grads:
            if not self._fused_eligible(p):
                leftover.append((p, g))
                continue
            idx = idx_of[id(p)]
            lr_mult = float(getattr(p, "_lr_scale", 1.0))
            if self._lr_ratio is not None:
                lr_mult *= float(self._lr_ratio(p))
            decay_on = self._wd != 0.0 and (
                self._apply_decay_fn is None
                or self._apply_decay_fn(p.name or f"param_{idx}"))
            key = (str(p._data.dtype), lr_mult, bool(decay_on))
            groups.setdefault(key, []).append((idx, p, g._data))

        if not groups:
            return leftover
        ordered = sorted(groups.items(), key=lambda kv: kv[1][0][0])
        # geometry guard: any layout drift de-bucketizes everything back
        # to the canonical slots and rebuilds — moments survive the
        # migration. Two triggers: (a) an existing group's sig changed
        # (new/lost grads in it, dtype or sharding-degree change, uid
        # shift from group reordering); (b) a whole group VANISHED —
        # its bucket would otherwise linger under a uid a new group can
        # be assigned, silently adopting or clobbering foreign moments
        rebuild = bool(set(self._fused_buckets) - {k for k, _ in ordered})
        for uid, (key, members) in enumerate(ordered):
            sig = (uid, degree,
                   tuple((idx, p._data.shape) for idx, p, _ in members))
            rec = self._fused_buckets.get(key)
            if rec is not None and rec["sig"] != sig:
                rebuild = True
        if rebuild:
            self._drop_fused_buckets(debucketize=True)

        for uid, (key, members) in enumerate(ordered):
            param_dtype, lr_mult, decay_on = key
            lr_eff = lr * lr_mult
            rec = self._fused_buckets.get(key)
            if rec is None:
                layout = build_bucket_layout(
                    [(idx, p._data.shape) for idx, p, _ in members],
                    sharding_degree=degree)
                sig = (uid, degree,
                       tuple((idx, p._data.shape) for idx, p, _ in members))
                rec = {"uid": uid, "layout": layout, "sig": sig}
                self._fused_buckets[key] = rec
            layout = rec["layout"]
            has_master = jnp.dtype(param_dtype) != jnp.float32
            mdtype = self._moment_dtype if self._moment_dtype is not None \
                else jnp.float32
            self._seed_fused_bucket(uid, layout, members, mdtype,
                                    has_master, mesh)
            g_bucket = pack_bucket([g for _, _, g in members], layout,
                                   jnp.dtype(param_dtype))
            if has_master:
                w_bucket = self._accumulators["fused_master"][uid]
            else:
                w_bucket = pack_bucket([p._data for _, p, _ in members],
                                       layout, jnp.float32)
            m_bucket = self._accumulators["fused_m"][uid]
            v_bucket = self._accumulators["fused_v"][uid]
            scalars = adamw_scalars(lr_eff, self._beta1, self._beta2,
                                    self._eps,
                                    self._wd if decay_on else 0.0,
                                    self._step_count)
            if mesh is not None:
                p_new, w_new, m_new, v_new = fused_adamw_zero1(
                    g_bucket, w_bucket, m_bucket, v_bucket, scalars, mesh,
                    param_dtype=jnp.dtype(param_dtype) if has_master
                    else None)
            else:
                p_new, w_new, m_new, v_new = fused_adamw_bucket(
                    g_bucket, w_bucket, m_bucket, v_bucket, scalars,
                    param_dtype=jnp.dtype(param_dtype) if has_master
                    else None)
            self._accumulators["fused_m"][uid] = m_new
            self._accumulators["fused_v"][uid] = v_new
            if has_master:
                self._accumulators["fused_master"][uid] = w_new
            for arr, (_, p, _) in zip(unpack_bucket(p_new, layout), members):
                p._data = arr
        return leftover

    def _seed_fused_bucket(self, uid, layout, members, mdtype,
                           has_master, mesh):
        """Materialize a group's m/v (+ master) buckets if absent —
        from the canonical per-parameter slots when present (checkpoint
        reload / migration from the eager path), else zeros / fp32
        param casts, matching the eager accumulators' init exactly.
        Consumed per-parameter entries are removed so state never
        exists twice. Sharded placement happens at creation; once
        placed, updates inherit the layout (no per-step device_put)."""
        from ..kernels.fused_optimizer import pack_bucket, LANES
        m_slot = self._accumulators.setdefault("fused_m", {})
        v_slot = self._accumulators.setdefault("fused_v", {})
        w_slot = self._accumulators.setdefault("fused_master", {})

        def place(arr):
            if mesh is None:
                return arr
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.device_put(
                arr, NamedSharding(mesh, P("sharding", None)))

        shape = (layout.rows, LANES)
        for slot, canon, dtype in ((m_slot, "moment1", mdtype),
                                   (v_slot, "moment2", mdtype)):
            cur = slot.get(uid)
            if cur is not None and cur.shape == shape and cur.dtype == dtype:
                continue
            canon_slot = self._accumulators.get(canon, {})
            parts = []
            for idx, p, _ in members:
                prev = canon_slot.pop(idx, None)
                parts.append(jnp.zeros(p._data.shape, dtype) if prev is None
                             else prev.astype(dtype))
            slot[uid] = place(pack_bucket(parts, layout, dtype))
        if has_master:
            cur = w_slot.get(uid)
            if cur is None or cur.shape != shape:
                parts = []
                for idx, p, _ in members:
                    prev = self._master_weights.pop(idx, None)
                    parts.append(p._data.astype(jnp.float32)
                                 if prev is None else prev)
                w_slot[uid] = place(pack_bucket(parts, layout, jnp.float32))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        acc = self._acc("moment", idx, m_w, fill=self._init_acc)
        acc = acc + g * g
        self._set_acc("moment", idx, acc)
        self._writeback(idx, p, m_w - lr * g / (jnp.sqrt(acc) + self._eps))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps, self._rho = epsilon, rho

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        avg_sq = self._acc("avg_squared_grad", idx, m_w)
        avg_up = self._acc("avg_squared_update", idx, m_w)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * g * g
        update = -jnp.sqrt((avg_up + self._eps) / (avg_sq + self._eps)) * g
        avg_up = self._rho * avg_up + (1 - self._rho) * update * update
        self._set_acc("avg_squared_grad", idx, avg_sq)
        self._set_acc("avg_squared_update", idx, avg_up)
        self._writeback(idx, p, m_w + lr * update)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        m = self._acc("moment", idx, m_w)
        u = self._acc("inf_norm", idx, m_w)
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        self._set_acc("moment", idx, m)
        self._set_acc("inf_norm", idx, u)
        t = self._step_count
        self._writeback(idx, p,
                        m_w - lr / (1 - self._beta1 ** t) * m / (u + self._eps))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        ms = self._acc("mean_square", idx, m_w)
        mom = self._acc("momentum", idx, m_w)
        ms = self._rho * ms + (1 - self._rho) * g * g
        self._set_acc("mean_square", idx, ms)
        if self._centered:
            mg = self._acc("mean_grad", idx, m_w)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", idx, mg)
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * mom + lr * g / denom
        self._set_acc("momentum", idx, mom)
        self._writeback(idx, p, m_w - mom)


class Lamb(Optimizer):
    """Parity: python/paddle/optimizer/lamb.py (layerwise adaptive scaling)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, idx, p, g, lr):
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        m = self._acc("moment1", idx, m_w)
        v = self._acc("moment2", idx, m_w)
        t = self._step_count
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", idx, m)
        self._set_acc("moment2", idx, v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        r = r + wd * m_w
        w_norm = jnp.linalg.norm(m_w)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._writeback(idx, p, m_w - lr * trust * r)


class NAdam(Adam):
    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        m = self._acc("moment1", idx, m_w)
        v = self._acc("moment2", idx, m_w)
        t = self._step_count
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", idx, m)
        self._set_acc("moment2", idx, v)
        mhat = self._beta1 * m / (1 - self._beta1 ** (t + 1)) + \
            (1 - self._beta1) * g / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        self._writeback(idx, p, m_w - lr * mhat / (jnp.sqrt(vhat) + self._eps))


class RAdam(Adam):
    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        m = self._acc("moment1", idx, m_w)
        v = self._acc("moment2", idx, m_w)
        t = self._step_count
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", idx, m)
        self._set_acc("moment2", idx, v)
        mhat = m / (1 - self._beta1 ** t)
        rho_inf = 2 / (1 - self._beta2) - 1
        rho_t = rho_inf - 2 * t * self._beta2 ** t / (1 - self._beta2 ** t)
        if rho_t > 4:
            vhat = jnp.sqrt(v / (1 - self._beta2 ** t))
            rt = ((rho_t - 4) * (rho_t - 2) * rho_inf /
                  ((rho_inf - 4) * (rho_inf - 2) * rho_t)) ** 0.5
            self._writeback(idx, p, m_w - lr * rt * mhat / (vhat + self._eps))
        else:
            self._writeback(idx, p, m_w - lr * mhat)


class ASGD(Optimizer):
    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _apply_one(self, idx, p, g, lr):
        g = self._decayed_grad(p, g)
        m_w = self._master(idx, p)
        self._writeback(idx, p, m_w - lr * g.astype(m_w.dtype))


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _apply_one(self, idx, p, g, lr):
        m_w = self._master(idx, p)
        g = g.astype(m_w.dtype)
        prev_g = self._acc("prev_grad", idx, m_w)
        step = self._acc("step_size", idx, m_w, fill=self.get_lr())
        sign = jnp.sign(g * prev_g)
        step = jnp.where(sign > 0, jnp.minimum(step * self._etas[1], self._lr_range[1]),
                         jnp.where(sign < 0,
                                   jnp.maximum(step * self._etas[0], self._lr_range[0]),
                                   step))
        g_eff = jnp.where(sign < 0, jnp.zeros_like(g), g)
        self._set_acc("prev_grad", idx, g_eff)
        self._set_acc("step_size", idx, step)
        self._writeback(idx, p, m_w - jnp.sign(g_eff) * step)


class LBFGS(Optimizer):
    """Simplified LBFGS (single tensor-group, history-based two-loop)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-07, tolerance_change=1e-09, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._history_size = history_size
        self._s_hist: List = []
        self._y_hist: List = []
        self._prev_flat = None
        self._prev_grad = None

    def _flatten(self, arrays):
        return jnp.concatenate([a.reshape(-1) for a in arrays])

    def step(self, closure=None):
        if closure is not None:
            with autograd.enable_grad():
                loss = closure()
        params = [p for p in self._parameter_list
                  if not p.stop_gradient and p._grad_buffer is not None]
        if not params:
            return
        flat_g = self._flatten([p._grad_buffer.astype(jnp.float32) for p in params])
        flat_w = self._flatten([p._data.astype(jnp.float32) for p in params])
        if self._prev_flat is not None:
            s = flat_w - self._prev_flat
            y = flat_g - self._prev_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._s_hist.append(s)
                self._y_hist.append(y)
                if len(self._s_hist) > self._history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
        q = flat_g
        alphas = []
        for s, y in zip(reversed(self._s_hist), reversed(self._y_hist)):
            rho = 1.0 / jnp.dot(y, s)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._s_hist:
            s, y = self._s_hist[-1], self._y_hist[-1]
            q = q * (jnp.dot(s, y) / jnp.dot(y, y))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        direction = -q
        lr = self.get_lr()
        self._prev_flat = flat_w + lr * direction
        self._prev_grad = flat_g
        off = 0
        new_flat = self._prev_flat
        for p in params:
            n = p.size
            p._data = new_flat[off:off + n].reshape(p._data.shape).astype(p.dtype)
            off += n
        return None
