"""paddle_tpu.optimizer — parity with python/paddle/optimizer/."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adagrad, Adadelta, Adamax,
    RMSProp, Lamb, NAdam, RAdam, ASGD, Rprop, LBFGS,
)
