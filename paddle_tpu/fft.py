"""paddle.fft — discrete Fourier transforms.

Parity: reference `python/paddle/fft.py` (delegating to phi fft kernels /
pocketfft). TPU-native: jnp.fft lowers to XLA's FFT HLO; every call goes
through the dispatch funnel so transforms are differentiable on the tape.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops.dispatch import apply_op

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    return norm if norm in ("ortho", "forward") else "backward"


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return apply_op("fft", lambda a: jnp.fft.fft(a, n, axis, _norm(norm)), x)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return apply_op("ifft", lambda a: jnp.fft.ifft(a, n, axis, _norm(norm)), x)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op("fft2", lambda a: jnp.fft.fft2(a, s, axes, _norm(norm)), x)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op("ifft2",
                    lambda a: jnp.fft.ifft2(a, s, axes, _norm(norm)), x)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("fftn", lambda a: jnp.fft.fftn(a, s, axes, _norm(norm)), x)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("ifftn",
                    lambda a: jnp.fft.ifftn(a, s, axes, _norm(norm)), x)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return apply_op("rfft", lambda a: jnp.fft.rfft(a, n, axis, _norm(norm)), x)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return apply_op("irfft",
                    lambda a: jnp.fft.irfft(a, n, axis, _norm(norm)), x)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op("rfft2",
                    lambda a: jnp.fft.rfft2(a, s, axes, _norm(norm)), x)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op("irfft2",
                    lambda a: jnp.fft.irfft2(a, s, axes, _norm(norm)), x)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("rfftn",
                    lambda a: jnp.fft.rfftn(a, s, axes, _norm(norm)), x)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("irfftn",
                    lambda a: jnp.fft.irfftn(a, s, axes, _norm(norm)), x)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return apply_op("hfft", lambda a: jnp.fft.hfft(a, n, axis, _norm(norm)), x)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return apply_op("ihfft",
                    lambda a: jnp.fft.ihfft(a, n, axis, _norm(norm)), x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes), x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """Hermitian-input 2-D FFT (parity: paddle.fft.hfft2)."""
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-d Hermitian-input FFT (c2r): a FORWARD transform throughout —
    forward fftn over the leading axes + hfft on the last. Parity:
    paddle.fft.hfftn -> fftn_c2r (reference python/paddle/fft.py:883);
    ground truth for real y = hfftn(x): ihfftn(y) == x, and
    hfftn == real(fftn(hermitian-expanded x))."""
    def _f(a):
        if axes is not None:
            ax = tuple(axes)
        elif s is not None:
            ax = tuple(range(-len(s), 0))   # last len(s) axes
        else:
            ax = tuple(range(-a.ndim, 0))
        last = ax[-1]
        lead = ax[:-1]
        n_last = None if s is None else s[-1]
        if lead:
            lead_s = None if s is None else s[:-1]
            a = jnp.fft.fftn(a, s=lead_s, axes=lead,
                             norm=_norm(norm))
        return jnp.fft.hfft(a, n=n_last, axis=last, norm=_norm(norm))
    return apply_op("hfftn", _f, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-d inverse Hermitian FFT (r2c): an INVERSE transform throughout —
    ihfft on the last axis + ifftn over the leading axes. For real x this
    equals np.fft.ifftn(x)[..., :n//2+1] (the advisor's ground truth)."""
    def _f(a):
        if axes is not None:
            ax = tuple(axes)
        elif s is not None:
            ax = tuple(range(-len(s), 0))   # last len(s) axes
        else:
            ax = tuple(range(-a.ndim, 0))
        last = ax[-1]
        lead = ax[:-1]
        n_last = None if s is None else s[-1]
        out = jnp.fft.ihfft(a, n=n_last, axis=last, norm=_norm(norm))
        if lead:
            lead_s = None if s is None else s[:-1]
            out = jnp.fft.ifftn(out, s=lead_s, axes=lead,
                                norm=_norm(norm))
        return out
    return apply_op("ihfftn", _f, x)
