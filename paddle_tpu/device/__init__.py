"""Device API.

Parity: reference `python/paddle/device/` — set_device/get_device, device
counts, synchronization, memory stats. Streams/events collapse: XLA owns
scheduling on TPU; synchronize == block_until_ready on a probe array.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["set_device", "get_device", "get_all_custom_device_type",
           "is_compiled_with_cuda", "is_compiled_with_xpu",
           "is_compiled_with_rocm", "is_compiled_with_custom_device",
           "device_count", "synchronize", "get_available_device", "cuda",
           "Stream", "Event", "current_stream", "stream_guard"]

_current_device = [None]


def set_device(device: str):
    """Accepts 'tpu', 'cpu', 'tpu:0' etc. Device residency in jax follows
    data placement; this sets the default placement hint."""
    name = device.split(":")[0]
    _current_device[0] = device
    return device


def get_device():
    if _current_device[0] is not None:
        return _current_device[0]
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type="tpu"):
    return device_type in ("tpu", "axon")


def device_count():
    return jax.device_count()


def synchronize(device=None):
    jnp.zeros(()).block_until_ready()


class Stream:
    """No-op stream (XLA schedules internally). Kept for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_default_stream = Stream()


def current_stream(device=None):
    return _default_stream


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *a):
        return False


class _CudaNamespace:
    """paddle.device.cuda compatibility: returns empty/zero values on TPU."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def current_stream(device=None):
        return Stream()

    @staticmethod
    def synchronize(device=None):
        import jax
        import jax.numpy as jnp
        jnp.zeros(()).block_until_ready()

    @staticmethod
    def stream_guard(stream):
        import contextlib
        return contextlib.nullcontext(stream)

    @staticmethod
    def get_device_properties(device=None):
        import jax
        d = jax.devices()[0]
        class _Props:
            name = getattr(d, "device_kind", d.platform)
            major, minor = 0, 0
            total_memory = 0
            multi_processor_count = 0
        try:
            _Props.total_memory = int((d.memory_stats() or {}).get(
                "bytes_limit", 0))
        except Exception:
            pass
        return _Props()

    @staticmethod
    def get_device_name(device=None):
        import jax
        d = jax.devices()[0]
        return getattr(d, "device_kind", d.platform)

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)

    Stream = Stream
    Event = Event


def _mem_stats():
    try:
        return jax.devices()[0].memory_stats() or {}
    except Exception:
        return {}


cuda = _CudaNamespace()


# ----------------------------------------------------------- memory stats
# Parity: reference memory stats API (`paddle/phi/core/memory/stats.h`,
# `paddle.device.cuda.max_memory_allocated`). On TPU the allocator is
# XLA's: per-device counters come from PJRT `Device.memory_stats()`
# (bytes_in_use / peak_bytes_in_use). Where the backend doesn't publish
# stats (CPU, tunneled devices), fall back to summing live jax arrays and
# track the peak as a high-water mark over observations.
_mem_peaks = {}   # per-device high-water mark of observed bytes_in_use
_mem_floor = {}   # backend peak counter value at the last reset()


def _device_obj(device=None):
    if device is None or isinstance(device, (int,)):
        return jax.local_devices()[device or 0]
    return device


def _live_bytes(dev):
    total = 0
    for arr in jax.live_arrays():
        try:
            for sh in arr.addressable_shards:
                if sh.device == dev:
                    total += int(sh.data.size) * sh.data.dtype.itemsize
        except Exception:
            continue
    return total


def memory_stats(device=None):
    """Raw per-device allocator stats dict (may be backend-limited).

    The backend's peak_bytes_in_use counter is monotone over the process
    lifetime; reset_max_memory_allocated() records it as a floor, and the
    reported peak after a reset is the backend counter only once it rises
    above the floor (otherwise the best-effort max of bytes_in_use
    observations since the reset)."""
    dev = _device_obj(device)
    stats = dev.memory_stats()
    if stats is None:
        stats = {"bytes_in_use": _live_bytes(dev)}
    key = id(dev)
    in_use = stats.get("bytes_in_use", 0)
    backend_peak = stats.get("peak_bytes_in_use", 0)
    floor = _mem_floor.get(key, 0)
    peak = max(_mem_peaks.get(key, 0), in_use,
               backend_peak if backend_peak > floor else 0)
    _mem_peaks[key] = peak
    stats["peak_bytes_in_use"] = peak
    return stats


def memory_allocated(device=None):
    """Bytes currently allocated on the device.
    Parity: paddle.device.cuda.memory_allocated."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    """Peak allocated bytes. Parity: cuda.max_memory_allocated."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    """Bytes reserved by the allocator pool (== limit when published).
    Parity: cuda.memory_reserved."""
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_limit",
                                             s.get("bytes_in_use", 0))))


def max_memory_reserved(device=None):
    s = memory_stats(device)
    return int(s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0)))


def reset_max_memory_allocated(device=None):
    dev = _device_obj(device)
    _mem_peaks[id(dev)] = 0
    stats = dev.memory_stats() or {}
    # remember the monotone backend counter so pre-reset peaks don't leak
    # into post-reset reads
    _mem_floor[id(dev)] = stats.get("peak_bytes_in_use", 0)


def reset_max_memory_reserved(device=None):
    reset_max_memory_allocated(device)


__all__ += ["memory_stats", "memory_allocated", "max_memory_allocated",
            "memory_reserved", "max_memory_reserved",
            "reset_max_memory_allocated", "reset_max_memory_reserved"]


def get_cudnn_version():
    """None: no cuDNN in the TPU build (parity probe)."""
    return None


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    """The fusion-compiler capability is XLA in this build."""
    return False


def is_compiled_with_distribute():
    """Distributed support is always compiled in (XLA collectives)."""
    return True


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_custom_device():
    return []


def set_stream(stream=None):
    """Streams are XLA-managed; kept for API parity."""
    return stream


from ..compat import XPUPlace  # noqa: E402,F401  (shared _Place base)


class IPUPlace:
    def __init__(self):
        raise NotImplementedError("IPU backends are not part of this build")
