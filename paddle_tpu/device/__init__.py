"""Device API.

Parity: reference `python/paddle/device/` — set_device/get_device, device
counts, synchronization, memory stats. Streams/events collapse: XLA owns
scheduling on TPU; synchronize == block_until_ready on a probe array.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["set_device", "get_device", "get_all_custom_device_type",
           "is_compiled_with_cuda", "is_compiled_with_xpu",
           "is_compiled_with_rocm", "is_compiled_with_custom_device",
           "device_count", "synchronize", "get_available_device", "cuda",
           "Stream", "Event", "current_stream", "stream_guard"]

_current_device = [None]


def set_device(device: str):
    """Accepts 'tpu', 'cpu', 'tpu:0' etc. Device residency in jax follows
    data placement; this sets the default placement hint."""
    name = device.split(":")[0]
    _current_device[0] = device
    return device


def get_device():
    if _current_device[0] is not None:
        return _current_device[0]
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type="tpu"):
    return device_type in ("tpu", "axon")


def device_count():
    return jax.device_count()


def synchronize(device=None):
    jnp.zeros(()).block_until_ready()


class Stream:
    """No-op stream (XLA schedules internally). Kept for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_default_stream = Stream()


def current_stream(device=None):
    return _default_stream


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *a):
        return False


class _CudaNamespace:
    """paddle.device.cuda compatibility: returns empty/zero values on TPU."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def max_memory_allocated(device=None):
        return _mem_stats().get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        return _mem_stats().get("bytes_in_use", 0)

    @staticmethod
    def empty_cache():
        pass

    Stream = Stream
    Event = Event


def _mem_stats():
    try:
        return jax.devices()[0].memory_stats() or {}
    except Exception:
        return {}


cuda = _CudaNamespace()
