"""Checkpoint converters: load PaddleNLP / HuggingFace Llama weights.

Parity: the reference trains Llama through PaddleNLP recipes whose
checkpoints use the `llama.*` key prefix with (in, out) Linear layout;
HF transformers checkpoints use `model.*` keys with (out, in) torch
layout. SURVEY.md §7 lists the name-mapping story as the checkpoint
compat requirement for recipe parity — a reference user's weights must
load into this framework unchanged.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["convert_llama_state_dict", "load_llama_checkpoint"]

# our canonical key template (LlamaForCausalLM.state_dict)
_LAYER_SUFFIXES = [
    "self_attn.q_proj.weight", "self_attn.k_proj.weight",
    "self_attn.v_proj.weight", "self_attn.o_proj.weight",
    "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight",
    "input_layernorm.weight", "post_attention_layernorm.weight",
]


def _detect_source(keys):
    if any(k.startswith("llama.") for k in keys):
        return "paddlenlp"
    if any(k.startswith("model.layers.") or k == "model.embed_tokens.weight"
           for k in keys):
        return "hf"
    return "native"


def convert_llama_state_dict(state_dict: Dict, dtype=None) -> Dict:
    """Map a PaddleNLP (`llama.*`, (in, out) layout) or HuggingFace
    (`model.*`, (out, in) torch layout) Llama checkpoint onto this
    framework's key space. Values may be numpy arrays or Tensors; returns
    {our_key: np.ndarray}."""
    raw = {k: (np.asarray(v._data) if isinstance(v, Tensor) else
               np.asarray(v)) for k, v in state_dict.items()}
    src = _detect_source(raw.keys())
    if src == "native":
        return raw

    out: Dict[str, np.ndarray] = {}
    prefix = "llama." if src == "paddlenlp" else "model."
    transpose = src == "hf"  # torch Linear stores (out, in)

    def put(our_key, src_key, is_linear=False):
        if src_key not in raw:
            return
        w = raw[src_key]
        if is_linear and transpose and w.ndim == 2:
            w = w.T
        out[our_key] = w

    put("model.embed_tokens.weight", prefix + "embed_tokens.weight")
    put("model.norm.weight", prefix + "norm.weight")
    put("lm_head.weight", "lm_head.weight", is_linear=True)
    # PaddleNLP lm_head is (hidden, vocab) already — matches ours
    i = 0
    while f"{prefix}layers.{i}.input_layernorm.weight" in raw:
        for suf in _LAYER_SUFFIXES:
            put(f"model.layers.{i}.{suf}", f"{prefix}layers.{i}.{suf}",
                is_linear=suf.endswith("proj.weight"))
            bias_suf = suf.replace(".weight", ".bias")
            if f"{prefix}layers.{i}.{bias_suf}" in raw:
                put(f"model.layers.{i}.{bias_suf}",
                    f"{prefix}layers.{i}.{bias_suf}")
        i += 1
    if dtype is not None:
        out = {k: v.astype(dtype) for k, v in out.items()}
    return out


def load_llama_checkpoint(model, state_dict: Dict, strict: bool = False):
    """Convert + load into a LlamaForCausalLM (or Pipe) instance.
    Returns (missing_keys, unexpected_keys)."""
    converted = convert_llama_state_dict(state_dict)
    own = model.state_dict()
    missing, loaded = [], set()
    for k, t in own.items():
        if k.startswith("model.rope_"):
            continue  # recomputed buffers
        if k in converted:
            arr = jnp.asarray(converted[k])
            if tuple(arr.shape) != tuple(t._data.shape):
                raise ValueError(
                    f"{k}: checkpoint shape {arr.shape} != model "
                    f"{tuple(t._data.shape)}")
            t._data = arr.astype(t._data.dtype)
            loaded.add(k)
        else:
            missing.append(k)
    unexpected = [k for k in converted if k not in own]
    if strict and (missing or unexpected):
        raise KeyError(f"missing={missing} unexpected={unexpected}")
    return missing, unexpected
