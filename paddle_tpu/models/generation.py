"""Compiled autoregressive generation: one jit program for the whole
decode loop.

Capability parity: the reference serves generation through PaddleNLP's
`generate` + the fused serving kernels (`block_multi_head_attention`,
`masked_multihead_attention`, `top_p_sampling` — SURVEY.md A.2); this is
the framework-native equivalent.

TPU-first design: the KV cache is a FIXED-size buffer written at a
position (no per-step reallocation/recompile); prefill + every decode
step + sampling live inside ONE `jax.jit` whose decode loop is a
`lax.while_loop` with early exit when every sequence hit EOS. Sampling
supports temperature / top-k / top-p (nucleus) entirely on device — no
host sync until the final buffer readback. Compiled programs are cached
per (model, B, S0, N, sampling config).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["jit_generate"]


def _filter_logits(logits, temperature, top_k, top_p):
    """Temperature + top-k + top-p (nucleus) filtering over the last
    axis; leading axes are batched. Returns float32 filtered logits
    (masked-out entries at -inf). Shared by `_sample_arr` and the
    serving spec-decode verify program, whose rejection sampling needs
    the filtered DISTRIBUTION, not just one draw."""
    lg = logits.astype(jnp.float32) / temperature
    V = lg.shape[-1]
    if top_k and top_k < V:
        kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if top_p < 1.0:
        sorted_lg = jnp.sort(lg, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest logit still inside the nucleus. NOTE: this was
        # jnp.max over the kept logits until ISSUE 5 — which reduces to
        # the single argmax whenever top_p < 1 (the nucleus collapsed
        # to one token); spec-decode rejection sampling consumes this
        # distribution directly, which is how the bug surfaced
        keep = (cum - probs) < top_p
        kth = jnp.min(jnp.where(keep, sorted_lg, jnp.inf), axis=-1,
                      keepdims=True)
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    return lg


def _sample_arr(logits, key, temperature, top_k, top_p):
    """(B, V) logits -> (B,) int32 token ids, pure-array."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = _filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def _build_program(model, B, S0, N, temperature, top_k, top_p, eos):
    from ..jit.api import functional_call

    L = model.cfg.num_hidden_layers
    KV = model.cfg.num_key_value_heads
    D = model.cfg.hidden_size // model.cfg.num_attention_heads
    MAX = S0 + N
    param_dtype = next(iter(model.state_dict().values()))._data.dtype

    def run_model(state_a, ids, caches_a, pos):
        st = {k: Tensor(v) for k, v in state_a.items()}
        caches_t = [(Tensor(kc), Tensor(vc)) for kc, vc in caches_a]
        logits, new_caches = functional_call(
            model, st, Tensor(ids), caches=caches_t, cache_pos=pos)
        return (logits._data,
                [(c[0]._data, c[1]._data) for c in new_caches])

    def program(state_a, ids, key):
        caches = [(jnp.zeros((B, MAX, KV, D), param_dtype),
                   jnp.zeros((B, MAX, KV, D), param_dtype))
                  for _ in range(L)]
        logits, caches = run_model(state_a, ids, caches, jnp.int32(0))
        key, k0 = jax.random.split(key)
        tok = _sample_arr(logits[:, -1], k0, temperature, top_k, top_p)
        # pre-fill the generated region with eos (or 0) so an early
        # all-done exit leaves correct padding without extra writes
        fill = eos if eos is not None else 0
        ids_buf = jnp.concatenate(
            [ids, jnp.full((B, N), fill, ids.dtype)], axis=1)
        ids_buf = jax.lax.dynamic_update_slice(
            ids_buf, tok[:, None].astype(ids.dtype),
            (jnp.int32(0), jnp.int32(S0)))
        done = (tok == eos) if eos is not None else jnp.zeros((B,), bool)

        def cond(carry):
            _, _, _, t, _, done = carry
            return jnp.logical_and(t < N - 1,
                                   jnp.logical_not(jnp.all(done)))

        def body(carry):
            ids_buf, caches, tok, t, key, done = carry
            logits, caches = run_model(
                state_a, tok[:, None].astype(ids.dtype), caches,
                (S0 + t).astype(jnp.int32))
            key, kn = jax.random.split(key)
            nxt = _sample_arr(logits[:, 0], kn, temperature, top_k, top_p)
            if eos is not None:
                nxt = jnp.where(done, jnp.int32(eos), nxt)
                done = jnp.logical_or(done, nxt == eos)
            ids_buf = jax.lax.dynamic_update_slice(
                ids_buf, nxt[:, None].astype(ids.dtype),
                (jnp.int32(0), (S0 + t + 1).astype(jnp.int32)))
            return ids_buf, caches, nxt, t + 1, key, done

        ids_buf, _, _, _, _, _ = jax.lax.while_loop(
            cond, body, (ids_buf, caches, tok, jnp.int32(0), key, done))
        return ids_buf

    return jax.jit(program)


def jit_generate(model, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, top_p=1.0, eos_token_id=None, seed=None):
    """Generate with the whole decode loop compiled into one XLA program.

    model: a causal LM whose forward supports (input_ids, caches=...,
    cache_pos=...) fixed-buffer decoding (models/llama.py). Returns
    (B, S0 + max_new_tokens) ids; sequences that hit eos are padded with
    eos.
    """
    from ..core.autograd import no_grad
    from ..framework.random import rng_key

    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(input_ids)
    B, S0 = ids.shape
    if int(max_new_tokens) <= 0:
        return Tensor(ids)
    # cache lives ON the model: programs (whose closures hold the model)
    # form an ordinary self-cycle that the gc collects with the model
    per_model = model.__dict__.get("_generate_programs")
    if per_model is None:
        per_model = {}
        model.__dict__["_generate_programs"] = per_model
    cache_key = (B, S0, int(max_new_tokens), float(temperature),
                 int(top_k), float(top_p), eos_token_id)
    prog = per_model.get(cache_key)
    if prog is None:
        prog = _build_program(model, B, S0, int(max_new_tokens),
                              float(temperature), int(top_k), float(top_p),
                              eos_token_id)
        if len(per_model) >= 8:        # bounded per model
            per_model.pop(next(iter(per_model)))
        per_model[cache_key] = prog
    with no_grad():
        state_a = {k: t._data for k, t in model.state_dict().items()}
        key = (jax.random.PRNGKey(seed) if seed is not None else rng_key())
        out = prog(state_a, ids, key)
    return Tensor(out)
