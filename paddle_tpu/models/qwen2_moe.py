"""Qwen2-MoE decoder — the expert-parallel rung of the config ladder
(BASELINE.md: "Qwen2-MoE EP").

Capability parity: the reference trains Qwen2-MoE via PaddleNLP on the
incubate MoE stack (`python/paddle/incubate/distributed/models/moe/
moe_layer.py`, global_scatter/global_gather collectives); here the sparse
FFN is distributed.moe.MoELayer — capacity-bounded one-hot dispatch whose
expert dim is sharded over the 'model'(EP) mesh axis, so GSPMD emits the
all_to_all over ICI.

Architecture (Qwen2-MoE): Llama-style GQA attention with qkv bias, RoPE,
RMSNorm; each decoder layer's FFN = top-k routed experts + a
sigmoid-gated shared expert; load-balancing aux loss summed over layers.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mpu import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding)
from ..distributed.moe import MoELayer, TopKGate
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.dispatch import apply_op
from .llama import _rope_cache, apply_rotary

__all__ = ["Qwen2MoeConfig", "Qwen2MoeForCausalLM", "qwen2_moe_tiny",
           "qwen2_moe_a14b"]


@dataclass
class Qwen2MoeConfig:
    vocab_size: int = 151936
    hidden_size: int = 2048
    intermediate_size: int = 5632          # dense-layer FFN (unused if all sparse)
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 60
    num_experts_per_tok: int = 4
    decoder_sparse_step: int = 1           # every n-th layer is sparse
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    router_aux_loss_coef: float = 0.001
    capacity_factor: float = 2.0
    tie_word_embeddings: bool = False


def qwen2_moe_tiny(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               moe_intermediate_size=32, shared_expert_intermediate_size=64,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, num_experts=4, num_experts_per_tok=2,
               max_position_embeddings=128)
    cfg.update(kw)
    return Qwen2MoeConfig(**cfg)


def qwen2_moe_a14b(**kw):
    """Qwen2-57B-A14B geometry."""
    cfg = dict(vocab_size=151936, hidden_size=3584,
               moe_intermediate_size=2560,
               shared_expert_intermediate_size=20480,
               num_hidden_layers=28, num_attention_heads=28,
               num_key_value_heads=4, num_experts=64, num_experts_per_tok=8)
    cfg.update(kw)
    return Qwen2MoeConfig(**cfg)


class Qwen2MoeAttention(nn.Layer):
    """GQA with qkv bias (Qwen2 convention), RoPE, TP-sharded projections."""

    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        h = cfg.hidden_size
        self.head_dim = h // cfg.num_attention_heads
        self.n_heads = cfg.num_attention_heads
        self.n_kv = cfg.num_key_value_heads
        self.q_proj = ColumnParallelLinear(h, h, has_bias=True,
                                           gather_output=False)
        self.k_proj = ColumnParallelLinear(h, self.n_kv * self.head_dim,
                                           has_bias=True, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, self.n_kv * self.head_dim,
                                           has_bias=True, gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=False,
                                        input_is_parallel=True)

    def forward(self, x, cos, sin):
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.n_heads, self.head_dim])
        k = M.reshape(self.k_proj(x), [b, s, self.n_kv, self.head_dim])
        v = M.reshape(self.v_proj(x), [b, s, self.n_kv, self.head_dim])
        q = apply_op("rope", apply_rotary, q, cos, sin)
        k = apply_op("rope", apply_rotary, k, cos, sin)
        if self.n_kv != self.n_heads:
            rep = self.n_heads // self.n_kv
            k = apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2), k)
            v = apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2), v)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        return self.o_proj(out)


class _ExpertMLP(nn.Layer):
    """SwiGLU expert over (capacity, d) token slabs."""

    def __init__(self, hidden, inter):
        super().__init__()
        self.gate_proj = nn.Linear(hidden, inter, bias_attr=False)
        self.up_proj = nn.Linear(hidden, inter, bias_attr=False)
        self.down_proj = nn.Linear(inter, hidden, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class _SharedExpert(nn.Layer):
    """Always-on expert with a learned sigmoid gate (Qwen2-MoE)."""

    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        h = cfg.hidden_size
        i = cfg.shared_expert_intermediate_size
        self.gate_proj = ColumnParallelLinear(h, i, has_bias=False,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, i, has_bias=False,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(i, h, has_bias=False,
                                           input_is_parallel=True)
        self.shared_expert_gate = nn.Linear(h, 1, bias_attr=False)

    def forward(self, x):
        out = self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))
        gate = F.sigmoid(self.shared_expert_gate(x))
        return apply_op("shared_gate", lambda g, o: g * o, gate, out)


class Qwen2MoeSparseBlock(nn.Layer):
    """Routed experts + shared expert."""

    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        experts = [_ExpertMLP(cfg.hidden_size, cfg.moe_intermediate_size)
                   for _ in range(cfg.num_experts)]
        gate = TopKGate(cfg.hidden_size, cfg.num_experts,
                        topk=cfg.num_experts_per_tok,
                        capacity_factor=cfg.capacity_factor)
        self.moe = MoELayer(cfg.hidden_size, experts=experts, gate=gate,
                            topk=cfg.num_experts_per_tok,
                            capacity_factor=cfg.capacity_factor)
        self.shared_expert = _SharedExpert(cfg)

    @property
    def aux_loss(self):
        return self.moe.aux_loss

    def forward(self, x):
        return self.moe(x) + self.shared_expert(x)


class Qwen2MoeDecoderLayer(nn.Layer):
    def __init__(self, cfg: Qwen2MoeConfig, layer_idx: int):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          epsilon=cfg.rms_norm_eps)
        self.self_attn = Qwen2MoeAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_norm_eps)
        self.is_sparse = ((layer_idx + 1) % cfg.decoder_sparse_step == 0)
        if self.is_sparse:
            self.mlp = Qwen2MoeSparseBlock(cfg)
        else:
            from .llama import LlamaMLP, LlamaConfig
            self.mlp = _ExpertMLP(cfg.hidden_size, cfg.intermediate_size)

    def forward(self, x, cos, sin):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class Qwen2MoeModel(nn.Layer):
    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                   cfg.hidden_size)
        self.layers = nn.LayerList([Qwen2MoeDecoderLayer(cfg, i)
                                    for i in range(cfg.num_hidden_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = _rope_cache(head_dim, cfg.max_position_embeddings,
                               cfg.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        cos = apply_op("rope_slice", lambda c: c[:s], self.rope_cos)
        sin = apply_op("rope_slice", lambda c: c[:s], self.rope_sin)
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, cos, sin)
        return self.norm(x)

    def aux_losses(self):
        out = []
        for layer in self.layers:
            if layer.is_sparse and layer.mlp.aux_loss is not None:
                out.append(layer.mlp.aux_loss)
        return out


class Qwen2MoeForCausalLM(nn.Layer):
    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        self.cfg = cfg
        self.model = Qwen2MoeModel(cfg)
        self.lm_head = ColumnParallelLinear(cfg.hidden_size, cfg.vocab_size,
                                            has_bias=False,
                                            gather_output=False)

    def forward(self, input_ids, labels=None):
        h = self.model(input_ids)
        logits = self.lm_head(h)
        if labels is None:
            return logits
        from ..distributed.fleet.mpu import ParallelCrossEntropy
        shift_logits = apply_op("shift", lambda a: a[:, :-1, :], logits)
        shift_labels = apply_op("shift", lambda a: a[:, 1:], labels)
        loss_t = ParallelCrossEntropy()(shift_logits, shift_labels)

        def _masked_mean(l, lab):
            valid = (lab != -100).astype(l.dtype)
            return jnp.sum(l[..., 0] * valid) / jnp.maximum(
                jnp.sum(valid), 1.0)
        loss = apply_op("masked_mean", _masked_mean, loss_t, shift_labels)
        aux = self.model.aux_losses()
        if aux and self.cfg.router_aux_loss_coef > 0:
            total_aux = aux[0]
            for a in aux[1:]:
                total_aux = total_aux + a
            loss = loss + self.cfg.router_aux_loss_coef * total_aux
        return loss
