"""Llama-family decoder LM — the flagship pretraining model.

Capability parity: the reference trains Llama via PaddleNLP recipes on top
of fleet hybrid parallel (SURVEY.md §3.3); this module provides the model +
hybrid-parallel training step natively.

TPU-first design:
  * weights carry GSPMD shardings over the hybrid mesh axes
    ([data, pipe, sharding, sep, model]) via the fleet.mpu layers —
    ColumnParallel/RowParallel/VocabParallel place qkv/mlp/vocab exactly as
    Megatron-TP does, and XLA inserts the ICI collectives;
  * attention runs through nn.functional.scaled_dot_product_attention
    (Pallas flash kernel when eligible);
  * sequence parallelism = Shard over the 'sep' axis on the seq dim of
    activations (Ulysses-style alltoall emitted by GSPMD at the attention
    boundary);
  * the training step is compiled end-to-end with jit (fwd+bwd+AdamW).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mpu import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding, _constraint,
                                     current_mesh, mark_sharding)
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.dispatch import apply_op
from jax.sharding import PartitionSpec as P

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaForCausalLMPipe", "llama_tiny", "llama_3_8b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_bias: bool = False
    sequence_parallel: bool = False
    recompute: bool = False
    dtype: str = "float32"


def llama_tiny(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=128)
    cfg.update(kw)
    return LlamaConfig(**cfg)


def llama_3_8b(**kw):
    cfg = dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
               num_hidden_layers=32, num_attention_heads=32,
               num_key_value_heads=8, max_position_embeddings=8192,
               rope_theta=500000.0)
    cfg.update(kw)
    return LlamaConfig(**cfg)


def _rope_cache(head_dim, max_pos, theta, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # (S, D/2)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def _split_kv_args(arrs, n_tail):
    """Unpack a paged-cache apply_op arg list: (k, v[, k_scale,
    v_scale], *tail) -> (k, v, k_scale|None, v_scale|None, tail). The
    cache tuple's arity (2 full-width / 4 quantized, ISSUE 6) is the
    only thing that varies, so every paged write/attend closure shares
    this one splitter instead of forking per dtype."""
    kc, vc = arrs[0], arrs[1]
    scales = arrs[2:len(arrs) - n_tail]
    ks, vs = scales if scales else (None, None)
    return kc, vc, ks, vs, arrs[len(arrs) - n_tail:]


def _gather_kv(cache, bt, n_kv, hd, b, scale=None, cdt=None):
    """Gather a block table's pages into the dense (b, S, KVH, D) view
    the prefill/verify attention consumes; int8 caches dequantize
    during the gather (values * per-slot scales, cast to the compute
    dtype). bt is (P,) for the single-sequence prefill path and (B, P)
    for the batched verify path — the page->token transpose is the
    same swap either way."""
    idx = bt.astype(jnp.int32)
    g = jnp.take(cache, idx, axis=0)
    if scale is not None:
        g = g.astype(jnp.float32) * jnp.take(scale, idx, axis=0)[..., None]
    g = jnp.swapaxes(g, bt.ndim, bt.ndim + 1)   # (..., page, KVH, ...)
    g = g.reshape(b, -1, n_kv, hd)
    return g.astype(cdt) if scale is not None else g


def apply_rotary(x, cos, sin):
    """x: (B, S, H, D). Rotates pairs (even, odd) — GPT-J/Llama interleaved
    convention. The pairs are addressed by VIEWING D as (D/2, 2) rather
    than stride-2 lane slices (`x[..., 0::2]`): on TPU the minor dim is
    the 128-lane axis, and strided lane gathers ran at 320 GB/s vs
    788 GB/s (near HBM roofline) for the reshape form — measured on a
    v5e at (4, 2048, 12, 128); the math is bit-identical."""
    xr = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    x1 = xr[..., 0]
    x2 = xr[..., 1]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1)
    return out.reshape(x.shape)


def _lora(name, x, y):
    """Multi-LoRA serving hook (ISSUE 15): adds the active launch
    scope's per-row adapter delta to a projection output. With no
    scope active (training, lora-less serving) it returns `y`
    UNTOUCHED — the traced graph is exactly what it always was; the
    cost is one thread-local read per projection per trace."""
    from ..serving.lora.runtime import apply_lora
    return apply_lora(name, x, y)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        self.head_dim = h // config.num_attention_heads
        self.n_heads = config.num_attention_heads
        self.n_kv = config.num_key_value_heads
        self.q_proj = ColumnParallelLinear(h, h, has_bias=config.use_bias,
                                           gather_output=False)
        self.k_proj = ColumnParallelLinear(h, self.n_kv * self.head_dim,
                                           has_bias=config.use_bias,
                                           gather_output=False)
        self.v_proj = ColumnParallelLinear(h, self.n_kv * self.head_dim,
                                           has_bias=config.use_bias,
                                           gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=config.use_bias,
                                        input_is_parallel=True)

    def forward(self, x, cos, sin, cache=None, cache_pos=None):
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.n_heads, self.head_dim])
        k = M.reshape(self.k_proj(x), [b, s, self.n_kv, self.head_dim])
        v = M.reshape(self.v_proj(x), [b, s, self.n_kv, self.head_dim])
        q = apply_op("rope", apply_rotary, q, cos, sin)
        k = apply_op("rope", apply_rotary, k, cos, sin)
        if cache is not None and cache_pos is not None:
            # fixed-size cache buffers + write position: the jit-compiled
            # decode path (generate) — buffer shape never changes, so one
            # compiled program serves every step (lax.while_loop-able)
            pk, pv = cache
            pos = jnp.asarray(cache_pos, jnp.int32)

            def _write(buf, new):
                return jax.lax.dynamic_update_slice(
                    buf, new.astype(buf.dtype),
                    (jnp.int32(0), pos, jnp.int32(0), jnp.int32(0)))

            k = apply_op("cache_write", _write, pk, k)
            v = apply_op("cache_write", _write, pv, v)
            cache = (k, v)
            max_len = int(pk.shape[1])

            def _mask(_q):
                qpos = pos + jnp.arange(s, dtype=jnp.int32)
                kpos = jnp.arange(max_len, dtype=jnp.int32)
                return (kpos[None, :] <= qpos[:, None])[None, None]

            mask = apply_op("cache_mask", _mask, q)
        elif cache is not None:
            pk, pv = cache
            k = M.concat([pk, k], axis=1)
            v = M.concat([pv, v], axis=1)
            cache = (k, v)
            mask = None
        else:
            mask = None
        if self.n_kv != self.n_heads:
            rep = self.n_heads // self.n_kv
            k = apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2), k)
            v = apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2), v)
        # causal whenever we score more than one query position (prefill with
        # a cache included); single-token decode needs no mask. The sdpa
        # causal mask is key-offset-aware (tril with k=sk-sq). The fixed-
        # buffer path encodes causality + validity in its own bool mask.
        if mask is not None:
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=(s > 1))
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        out = self.o_proj(out)
        return (out, cache) if cache is not None else out

    def _gathered_dense(self, kv, block_tables, b, cdt):
        """Dense (b, S, KVH, D) K/V views of a sequence's gathered pages
        (the prefill/verify read path); quantized caches dequantize
        during the gather. One implementation for both the (P,)
        single-sequence and (B, P) batched block tables."""
        n_kv, hd = self.n_kv, self.head_dim
        if len(kv) == 4:
            def _g(cache, scale, bt):
                return _gather_kv(cache, bt, n_kv, hd, b,
                                  scale=scale, cdt=cdt)
            kd = apply_op("paged_gather_dequant", _g, kv[0], kv[2],
                          block_tables)
            vd = apply_op("paged_gather_dequant", _g, kv[1], kv[3],
                          block_tables)
        else:
            def _g(cache, bt):
                return _gather_kv(cache, bt, n_kv, hd, b)
            kd = apply_op("paged_gather", _g, kv[0], block_tables)
            vd = apply_op("paged_gather", _g, kv[1], block_tables)
        mesh = current_mesh()
        if mesh is not None and mesh.shape.get("model", 1) > 1:
            # TP serving: keep the gathered dense view sharded on the
            # kv-head axis (the caches' page contents are head-sharded,
            # so the gather never needs to materialize other shards'
            # heads)
            spec = P(None, None, "model", None)
            kd = apply_op("paged_gather_shard",
                          lambda a: _constraint(a, spec), kd)
            vd = apply_op("paged_gather_shard",
                          lambda a: _constraint(a, spec), vd)
        return kd, vd

    def forward_paged(self, x, cos_b, sin_b, kv, block_tables, seq_lens):
        """One decode step over the PAGED KV cache (serving engine path).

        x (B, 1, hidden); cos_b/sin_b (B, D/2) at each row's position;
        kv = (k_cache, v_cache) with caches (num_pages, KVH, page, D) —
        or the QUANTIZED 4-tuple (k, v, k_scale, v_scale) with int8
        value pages and (num_pages, KVH, page) fp32 per-slot scales
        (ISSUE 6); block_tables (B, max_pages); seq_lens (B,) INCLUDING
        the token being decoded. Writes the current token's K/V at
        position seq_lens-1 (quantize-on-write for int8), then attends
        through kernels.paged_attention_decode (dequantize-in-kernel).
        Returns (out, kv) with the updated cache tuple.
        """
        from ..kernels.paged_attention import (paged_attention_decode,
                                               paged_cache_write)
        b, s, _ = x.shape
        q = M.reshape(_lora("q_proj", x, self.q_proj(x)),
                      [b, s, self.n_heads, self.head_dim])
        k = M.reshape(_lora("k_proj", x, self.k_proj(x)),
                      [b, s, self.n_kv, self.head_dim])
        v = M.reshape(_lora("v_proj", x, self.v_proj(x)),
                      [b, s, self.n_kv, self.head_dim])
        q = apply_op("rope_pos", apply_rotary_positions, q, cos_b, sin_b)
        k = apply_op("rope_pos", apply_rotary_positions, k, cos_b, sin_b)

        def _write(*arrs):
            kc, vc, ks, vs, (kn, vn, bt, sl) = _split_kv_args(arrs, 4)
            return paged_cache_write(kc, vc, kn[:, 0], vn[:, 0], bt,
                                     sl.astype(jnp.int32) - 1,
                                     k_scale=ks, v_scale=vs)

        kv = apply_op("paged_cache_write", _write, *kv, k, v,
                      block_tables, seq_lens)

        def _attend(qq, *arrs):
            kc, vc, ks, vs, (bt, sl) = _split_kv_args(arrs, 2)
            mesh = current_mesh()
            if mesh is not None and mesh.shape.get("model", 1) > 1:
                # TP serving (ISSUE 8): heads/KV pages sharded over
                # 'model' — each shard attends its own head slice
                from ..kernels.paged_attention import \
                    paged_attention_decode_tp
                return paged_attention_decode_tp(
                    qq.reshape(b, self.n_heads, self.head_dim), kc, vc,
                    bt, sl, mesh, k_scale=ks, v_scale=vs)
            return paged_attention_decode(
                qq.reshape(b, self.n_heads, self.head_dim), kc, vc,
                bt, sl, k_scale=ks, v_scale=vs)

        out = apply_op("paged_attention_decode", _attend, q, *kv,
                       block_tables, seq_lens)
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        return _lora("o_proj", out, self.o_proj(out)), kv

    def forward_paged_prefill(self, x, cos_c, sin_c, kv,
                              block_table, cache_len, chunk_len):
        """One CHUNK of prompt prefill over the paged cache (the chunked
        prefill / prefix-cache serving path).

        x (1, S, hidden) holds tokens at absolute positions
        cache_len..cache_len+S-1, of which only the first chunk_len are
        live (the rest is bucket padding); cos_c/sin_c (S, D/2) are the
        rope rows already gathered at those absolute positions;
        kv = (k_cache, v_cache) or the quantized (k, v, k_scale,
        v_scale) tuple (int8 pages + fp32 per-slot scales, ISSUE 6);
        block_table (P,) is the sequence's page ids (PAD_PAGE-padded).
        Writes the chunk's roped K/V into the pages at offset cache_len
        (quantize-on-write for int8), then attends over the GATHERED
        dense view of the sequence's pages — the cached prefix
        [0, cache_len) plus the chunk itself, dequantized during the
        gather on the int8 path — with a position mask
        kpos <= cache_len + i. Prefill is compute-bound, so one XLA
        gather per layer is the right capability-axis cost; a fused
        chunk-attention Pallas kernel is a perf follow-up (BASELINE).
        Returns (out, kv).
        """
        from ..kernels.paged_attention import paged_cache_write_range
        b, s, _ = x.shape
        q = M.reshape(_lora("q_proj", x, self.q_proj(x)),
                      [b, s, self.n_heads, self.head_dim])
        k = M.reshape(_lora("k_proj", x, self.k_proj(x)),
                      [b, s, self.n_kv, self.head_dim])
        v = M.reshape(_lora("v_proj", x, self.v_proj(x)),
                      [b, s, self.n_kv, self.head_dim])
        q = apply_op("rope", apply_rotary, q, cos_c, sin_c)
        k = apply_op("rope", apply_rotary, k, cos_c, sin_c)

        def _write(*arrs):
            kc, vc, ks, vs, (kn, vn, bt, ln, st) = _split_kv_args(arrs, 5)
            return paged_cache_write_range(kc, vc, kn[0], vn[0], bt,
                                           ln, st, k_scale=ks, v_scale=vs)

        kv = apply_op("paged_cache_write_range", _write, *kv, k, v,
                      block_table, chunk_len, cache_len)
        kd, vd = self._gathered_dense(kv, block_table, 1, q._data.dtype)
        if self.n_kv != self.n_heads:
            rep = self.n_heads // self.n_kv
            kd = apply_op("repeat_kv",
                          lambda a: jnp.repeat(a, rep, axis=2), kd)
            vd = apply_op("repeat_kv",
                          lambda a: jnp.repeat(a, rep, axis=2), vd)
        sk = int(kd.shape[1])

        def _mask(cl):
            qpos = jnp.asarray(cl, jnp.int32) + jnp.arange(s, dtype=jnp.int32)
            kpos = jnp.arange(sk, dtype=jnp.int32)
            return (kpos[None, :] <= qpos[:, None])[None, None]

        mask = apply_op("chunk_mask", _mask, cache_len)
        out = F.scaled_dot_product_attention(q, kd, vd, attn_mask=mask)
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        return _lora("o_proj", out, self.o_proj(out)), kv

    def forward_paged_verify(self, x, cos_bs, sin_bs, kv,
                             block_tables, seq_lens, draft_lens):
        """One speculative VERIFY step over the paged cache: each row
        scores 1 + K tokens (the last emitted token plus K draft tokens)
        against its own paged prefix in ONE launch — the multi-token
        sibling of `forward_paged` (decode) built from the same pieces
        as `forward_paged_prefill` (gathered-prefix attention), batched.

        x (B, S, hidden): row b's tokens sit at absolute positions
        seq_lens[b]-1 .. seq_lens[b]-1+S-1, of which the first
        1 + draft_lens[b] are live (the rest is K-bucket padding);
        cos_bs/sin_bs (B, S, D/2) are rope rows pre-gathered at those
        positions; k/v_cache (num_pages, KVH, page, D); block_tables
        (B, max_pages); seq_lens (B,) counts tokens through the FIRST
        input token (the `forward_paged` convention — its position is
        seq_lens-1). kv = (k_cache, v_cache) or the quantized 4-tuple
        (ISSUE 6). Writes all live positions' roped K/V via
        `paged_cache_write_span` (idempotent for position seq_lens-1,
        like the decode write — quantize-on-write is deterministic, so
        retries and rollback-rewrites stay bit-identical), then attends
        over the gathered dense view of each row's pages (dequantized
        during the gather on the int8 path) under the causal mask
        kpos <= (seq_lens-1) + j. Returns (out, kv).
        """
        from ..kernels.paged_attention import paged_cache_write_span
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.n_heads, self.head_dim])
        k = M.reshape(self.k_proj(x), [b, s, self.n_kv, self.head_dim])
        v = M.reshape(self.v_proj(x), [b, s, self.n_kv, self.head_dim])
        q = apply_op("rope_span", apply_rotary_spans, q, cos_bs, sin_bs)
        k = apply_op("rope_span", apply_rotary_spans, k, cos_bs, sin_bs)

        def _write(*arrs):
            kc, vc, ks, vs, (kn, vn, bt, sl, dl) = _split_kv_args(arrs, 5)
            return paged_cache_write_span(
                kc, vc, kn, vn, bt,
                dl.astype(jnp.int32) + 1,            # live span tokens
                sl.astype(jnp.int32) - 1,            # first token's slot
                k_scale=ks, v_scale=vs)

        kv = apply_op("paged_cache_write_span", _write, *kv, k, v,
                      block_tables, seq_lens, draft_lens)
        kd, vd = self._gathered_dense(kv, block_tables, b, q._data.dtype)
        if self.n_kv != self.n_heads:
            rep = self.n_heads // self.n_kv
            kd = apply_op("repeat_kv",
                          lambda a: jnp.repeat(a, rep, axis=2), kd)
            vd = apply_op("repeat_kv",
                          lambda a: jnp.repeat(a, rep, axis=2), vd)
        sk = int(kd.shape[1])

        def _mask(sl):
            # padded batch rows carry seq_len 0 -> qpos would be -1 and
            # fully mask their first row (NaN softmax); clamp to 0 so
            # dead rows stay finite — their outputs are discarded
            qpos = jnp.maximum(
                sl.astype(jnp.int32)[:, None] - 1
                + jnp.arange(s, dtype=jnp.int32)[None, :], 0)   # (B, S)
            kpos = jnp.arange(sk, dtype=jnp.int32)
            return (kpos[None, None, :] <= qpos[:, :, None])[:, None]

        mask = apply_op("verify_mask", _mask, seq_lens)
        out = F.scaled_dot_product_attention(q, kd, vd, attn_mask=mask)
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        return self.o_proj(out), kv


def apply_rotary_spans(x, cos_bs, sin_bs):
    """Rotary at PER-ROW PER-OFFSET positions: x (B, S, H, D),
    cos_bs/sin_bs (B, S, D/2) gathered at each row's own span of
    absolute positions (the speculative-decode verify step scores
    1 + K tokens per sequence, each sequence at a different offset).
    Same pair-view convention as `apply_rotary`."""
    xr = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    x1 = xr[..., 0]
    x2 = xr[..., 1]
    c = cos_bs[:, :, None, :]
    s = sin_bs[:, :, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1)
    return out.reshape(x.shape)


def apply_rotary_positions(x, cos_b, sin_b):
    """Rotary at PER-ROW positions: x (B, 1, H, D), cos_b/sin_b (B, D/2)
    gathered at each row's own position (serving decode batches sequences
    of different lengths). Same pair-view convention as `apply_rotary`."""
    xr = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    x1 = xr[..., 0]
    x2 = xr[..., 1]
    c = cos_b[:, None, None, :]
    s = sin_b[:, None, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1)
    return out.reshape(x.shape)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, i, has_bias=config.use_bias,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, i, has_bias=config.use_bias,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(i, h, has_bias=config.use_bias,
                                           input_is_parallel=True)

    def forward(self, x):
        h = F.swiglu(_lora("gate_proj", x, self.gate_proj(x)),
                     _lora("up_proj", x, self.up_proj(x)))
        return _lora("down_proj", h, self.down_proj(h))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cos, sin, cache=None, cache_pos=None):
        h = self.input_layernorm(x)
        if cache is not None:
            attn, cache = self.self_attn(h, cos, sin, cache, cache_pos)
        else:
            attn = self.self_attn(h, cos, sin)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return (x, cache) if cache is not None else x

    def forward_paged(self, x, cos_b, sin_b, kv, block_tables, seq_lens):
        h = self.input_layernorm(x)
        attn, kv = self.self_attn.forward_paged(
            h, cos_b, sin_b, kv, block_tables, seq_lens)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, kv

    def forward_paged_prefill(self, x, cos_c, sin_c, kv,
                              block_table, cache_len, chunk_len):
        h = self.input_layernorm(x)
        attn, kv = self.self_attn.forward_paged_prefill(
            h, cos_c, sin_c, kv, block_table, cache_len, chunk_len)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, kv

    def forward_paged_verify(self, x, cos_bs, sin_bs, kv,
                             block_tables, seq_lens, draft_lens):
        h = self.input_layernorm(x)
        attn, kv = self.self_attn.forward_paged_verify(
            h, cos_bs, sin_bs, kv, block_tables, seq_lens, draft_lens)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, kv


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_cache(head_dim, config.max_position_embeddings,
                               config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, caches=None, cache_pos=None):
        s = input_ids.shape[1]
        if cache_pos is not None:
            past = jnp.asarray(cache_pos, jnp.int32)
        else:
            past = caches[0][0].shape[1] if caches is not None else 0
        cos = apply_op("rope_slice",
                       lambda c: jax.lax.dynamic_slice_in_dim(c, past, s, 0),
                       self.rope_cos)
        sin = apply_op("rope_slice",
                       lambda c: jax.lax.dynamic_slice_in_dim(c, past, s, 0),
                       self.rope_sin)
        x = self.embed_tokens(input_ids)
        if self.cfg.sequence_parallel:
            x = apply_op("sp_shard",
                         lambda a: _constraint(a, P("data", "sep", None)), x)
        new_caches = []
        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, cos, sin, caches[i], cache_pos)
                new_caches.append(c)
            elif self.cfg.recompute:
                x = _recompute_layer(layer, x, cos, sin)
            else:
                x = layer(x, cos, sin)
        x = self.norm(x)
        return (x, new_caches) if caches is not None else x

    def forward_paged_decode(self, input_ids, paged_caches, block_tables,
                             seq_lens):
        """One batched decode step over per-layer paged KV caches.

        input_ids (B, 1); paged_caches: list of per-layer cache tuples —
        (k_cache, v_cache), or (k, v, k_scale, v_scale) for int8 KV
        (ISSUE 6); seq_lens counts the token being decoded (its
        position is seq_lens-1). Returns (hidden (B, 1, H),
        new_caches) with the same tuple arity."""
        def _gather_rope(c, sl):
            return jnp.take(c, sl.astype(jnp.int32) - 1, axis=0)

        cos_b = apply_op("rope_gather", _gather_rope, self.rope_cos,
                         seq_lens)
        sin_b = apply_op("rope_gather", _gather_rope, self.rope_sin,
                         seq_lens)
        x = self.embed_tokens(input_ids)
        new_caches = []
        for i, layer in enumerate(self.layers):
            x, kv = layer.forward_paged(x, cos_b, sin_b, paged_caches[i],
                                        block_tables, seq_lens)
            new_caches.append(kv)
        return self.norm(x), new_caches

    def forward_paged_prefill(self, input_ids, paged_caches, block_table,
                              cache_len, chunk_len):
        """One prefill CHUNK over per-layer paged KV caches.

        input_ids (1, S) — prompt tokens at absolute positions
        cache_len..cache_len+S-1 (first chunk_len live, rest padding);
        block_table (P,) — the sequence's pages. Returns
        (hidden (1, S, H), new_caches). Chunked prefill and radix
        prefix-cache hits are the same program: a hit just starts at
        cache_len = matched tokens."""
        s = input_ids.shape[1]

        def _gather_rope(c, cl):
            pos = jnp.asarray(cl, jnp.int32) + jnp.arange(s, dtype=jnp.int32)
            # padded tail positions may run past the rope table; clip —
            # their rows are masked out of the attention anyway
            return jnp.take(c, jnp.clip(pos, 0, c.shape[0] - 1), axis=0)

        cos_c = apply_op("rope_gather", _gather_rope, self.rope_cos,
                         cache_len)
        sin_c = apply_op("rope_gather", _gather_rope, self.rope_sin,
                         cache_len)
        x = self.embed_tokens(input_ids)
        new_caches = []
        for i, layer in enumerate(self.layers):
            x, kv = layer.forward_paged_prefill(
                x, cos_c, sin_c, paged_caches[i], block_table, cache_len,
                chunk_len)
            new_caches.append(kv)
        return self.norm(x), new_caches

    def forward_paged_verify(self, input_ids, paged_caches, block_tables,
                             seq_lens, draft_lens):
        """One speculative VERIFY step over per-layer paged KV caches.

        input_ids (B, S) — row b holds [last emitted token,
        draft_1..draft_{S-1}] at absolute positions seq_lens[b]-1
        onward (first 1 + draft_lens[b] live, rest K-bucket padding);
        seq_lens counts tokens through the first input token (the
        `forward_paged_decode` convention). Returns
        (hidden (B, S, H), new_caches)."""
        s = input_ids.shape[1]

        def _gather_rope(c, sl):
            pos = (sl.astype(jnp.int32)[:, None] - 1
                   + jnp.arange(s, dtype=jnp.int32)[None, :])    # (B, S)
            # padded rows (seq_len 0) and padded span tails may run
            # off the table; clip — those rows are masked/discarded
            return jnp.take(c, jnp.clip(pos, 0, c.shape[0] - 1), axis=0)

        cos_bs = apply_op("rope_gather", _gather_rope, self.rope_cos,
                          seq_lens)
        sin_bs = apply_op("rope_gather", _gather_rope, self.rope_sin,
                          seq_lens)
        x = self.embed_tokens(input_ids)
        new_caches = []
        for i, layer in enumerate(self.layers):
            x, kv = layer.forward_paged_verify(
                x, cos_bs, sin_bs, paged_caches[i], block_tables,
                seq_lens, draft_lens)
            new_caches.append(kv)
        return self.norm(x), new_caches


def _recompute_layer(layer, x, cos, sin):
    """Activation checkpointing via jax.checkpoint over the layer's pure fn
    (parity: fleet/recompute/recompute.py RecomputeFunction)."""
    from ..jit.api import functional_call
    from ..kernels.flash_attention import _interpret_mode
    from ..nn.functional.flash_attention import sdp_kernel
    sd = layer.state_dict()
    keys = list(sd)
    # interpret-mode pallas calls can't be replayed by remat; real TPU keeps
    # the flash kernel inside the checkpointed region.
    use_flash = not _interpret_mode()

    def pure(params, xx, cc, ss):
        with sdp_kernel(enable_flash=use_flash):
            return functional_call(layer, dict(zip(keys, params)),
                                   Tensor(xx), Tensor(cc), Tensor(ss))._data

    ck = jax.checkpoint(pure, static_argnums=())
    return apply_op("recompute_layer",
                    lambda *arrs: ck(list(arrs[:len(keys)]), *arrs[len(keys):]),
                    *[sd[k] for k in keys], x, cos, sin)


def _head_and_loss(h, labels, lm_head, tied_weight):
    """LM head + shifted masked-mean cross entropy (shared by the plain and
    pipelined causal-LM heads)."""
    if lm_head is None:
        logits = apply_op("tied_head", lambda a, ww: a @ ww.T, h, tied_weight)
    else:
        logits = lm_head(h)
    if labels is None:
        return logits
    from ..distributed.fleet.mpu import ParallelCrossEntropy
    # next-token objective: logits[:, :-1] predict labels[:, 1:]
    shift_logits = apply_op("shift", lambda a: a[:, :-1, :], logits)
    shift_labels = apply_op("shift", lambda a: a[:, 1:], labels)
    loss_t = ParallelCrossEntropy()(shift_logits, shift_labels)

    # masked mean over valid (non-ignore_index) positions
    def _masked_mean(l, lab):
        valid = (lab != -100).astype(l.dtype)
        return jnp.sum(l[..., 0] * valid) / jnp.maximum(jnp.sum(valid), 1.0)

    return apply_op("masked_mean", _masked_mean, loss_t, shift_labels)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)

    def forward(self, input_ids, labels=None, caches=None, cache_pos=None):
        if caches is not None:
            h, caches = self.model(input_ids, caches, cache_pos)
        else:
            h = self.model(input_ids)
        tied = self.model.embed_tokens.weight if self.lm_head is None else None
        out = _head_and_loss(h, labels, self.lm_head, tied)
        if labels is not None:
            return out
        return (out, caches) if caches is not None else out

    def forward_paged_decode(self, input_ids, paged_caches, block_tables,
                             seq_lens):
        """Serving decode step: paged-KV transformer + LM head.
        Returns (logits (B, 1, V), new_caches)."""
        h, caches = self.model.forward_paged_decode(
            input_ids, paged_caches, block_tables, seq_lens)
        tied = self.model.embed_tokens.weight if self.lm_head is None else None
        logits = _head_and_loss(h, None, self.lm_head, tied)
        return logits, caches

    def forward_paged_prefill(self, input_ids, paged_caches, block_table,
                              cache_len, chunk_len):
        """Serving prefill chunk: paged-KV transformer + LM head at the
        chunk's LAST LIVE position only — the sole row serving consumes
        (and only on the final chunk at that); a full (S, V) head would
        spend ~S x the head FLOPs per chunk for nothing.
        Returns (logits (1, 1, V), new_caches)."""
        h, caches = self.model.forward_paged_prefill(
            input_ids, paged_caches, block_table, cache_len, chunk_len)

        def _last(hh, ln):
            return jax.lax.dynamic_slice_in_dim(
                hh, jnp.asarray(ln, jnp.int32) - 1, 1, axis=1)

        h_last = apply_op("chunk_last", _last, h, chunk_len)
        tied = self.model.embed_tokens.weight if self.lm_head is None else None
        logits = _head_and_loss(h_last, None, self.lm_head, tied)
        return logits, caches

    def forward_paged_verify(self, input_ids, paged_caches, block_tables,
                             seq_lens, draft_lens):
        """Serving speculative-verify step: paged-KV transformer over
        1 + K tokens per row + LM head at EVERY position — the verify
        consumer needs logits after each draft token (position j's
        logits score draft j+1 and supply the correction/bonus token),
        so unlike the chunk program the full (B, S, V) head is the
        point, not waste (S = K+1 is small). Returns
        (logits (B, S, V), new_caches)."""
        h, caches = self.model.forward_paged_verify(
            input_ids, paged_caches, block_tables, seq_lens, draft_lens)
        tied = self.model.embed_tokens.weight if self.lm_head is None else None
        logits = _head_and_loss(h, None, self.lm_head, tied)
        return logits, caches

    def forward_paged_decode_multi(self, input_ids, paged_caches,
                                   block_tables, seq_lens, step_caps,
                                   eos_ids, key, *, k_steps,
                                   temperature=0.0, top_k=0, top_p=1.0):
        """K decode iterations in ONE trace (multi-step device-side
        decode, ISSUE 13): a `lax.scan` over the single-token decode
        body with IN-GRAPH sampling, so one compiled launch emits up to
        `k_steps` tokens per row instead of paying the host round trip
        per token.

        input_ids (B,) int32 — each row's last emitted token; seq_lens
        (B,) counts through that token (the `forward_paged_decode`
        convention); step_caps (B,) int32 — tokens row b may emit this
        launch (0 marks a padded batch row; the engine caps by
        remaining max_new_tokens); eos_ids (B,) int32 per-row EOS
        (-1 = none); key — ONE pre-drawn PRNG key, per-step keys are
        `fold_in`(key, step) so StepSupervisor retries replay the
        identical launch bit-for-bit.

        Per-row freeze masks: a row stops emitting once it hits its
        cap, its EOS, or a non-finite logits row (the per-launch NaN
        quarantine signal). Frozen rows stay in the batch at frozen
        (ids, seq_len) — each remaining step rewrites the SAME token's
        K/V at the SAME position, the idempotent-rewrite contract the
        span writes already rely on — and their emitted-token slots are
        masked to the -1 sentinel. The loop carry threads the paged
        cache state through every step; the trip count is clamped to
        the tpu-lint A4 wedge cap (a 4096-iteration device-side loop
        once left the chip UNAVAILABLE for minutes; `k_steps` is
        engine-validated far below it, so the clamp is lint-provable,
        never load-bearing).

        Returns (tokens (B, K) int32 with -1 past each row's finish,
        n_emit (B,) int32, ok (B,) bool — False iff a LIVE step of that
        row produced non-finite logits — and the updated caches)."""
        from .generation import _sample_arr
        ids0 = (input_ids._data if isinstance(input_ids, Tensor)
                else jnp.asarray(input_ids)).astype(jnp.int32)
        bt = block_tables if isinstance(block_tables, Tensor) \
            else Tensor(jnp.asarray(block_tables))
        sl0 = (seq_lens._data if isinstance(seq_lens, Tensor)
               else jnp.asarray(seq_lens)).astype(jnp.int32)
        caps = (step_caps._data if isinstance(step_caps, Tensor)
                else jnp.asarray(step_caps)).astype(jnp.int32)
        eos = (eos_ids._data if isinstance(eos_ids, Tensor)
               else jnp.asarray(eos_ids)).astype(jnp.int32)
        key_a = key._data if isinstance(key, Tensor) else key
        b = ids0.shape[0]
        caches0 = [tuple(t._data for t in kv) for kv in paged_caches]

        def body(carry, j):
            ids, sl, active, n_emit, ok, caches = carry
            caches_t = [tuple(Tensor(a) for a in kv) for kv in caches]
            logits, new_caches = self.forward_paged_decode(
                Tensor(ids[:, None]), caches_t, bt, Tensor(sl))
            rows = logits._data[:, 0, :]
            fin = jnp.all(jnp.isfinite(rows), axis=-1)
            tok = _sample_arr(rows, jax.random.fold_in(key_a, j),
                              temperature, top_k, top_p)
            emit = jnp.logical_and(active, fin)
            # non-finite on a LIVE step poisons the row (frozen rows'
            # logits are discarded — they cannot quarantine anyone)
            ok = jnp.logical_and(ok, jnp.logical_or(fin, ~active))
            tok_out = jnp.where(emit, tok, jnp.int32(-1))
            n_emit = n_emit + emit.astype(jnp.int32)
            hit_eos = emit & (eos >= 0) & (tok == eos)
            active = emit & ~hit_eos & (n_emit < caps)
            ids = jnp.where(emit, tok, ids)
            sl = sl + emit.astype(jnp.int32)
            caches = [tuple(t._data for t in kv) for kv in new_caches]
            return (ids, sl, active, n_emit, ok, caches), tok_out

        carry0 = (ids0, sl0, caps > 0, jnp.zeros((b,), jnp.int32),
                  jnp.ones((b,), bool), caches0)
        # trip count clamped to the A4 wedge cap inline, so tpu-lint can
        # prove the bound statically (the engine validates k_steps far
        # below it — the min() is never load-bearing at runtime)
        steps = jnp.arange(min(int(k_steps), 512), dtype=jnp.int32)
        (_, _, _, n_emit, ok, caches), toks = jax.lax.scan(
            body, carry0, steps)
        new_caches = [tuple(Tensor(a) for a in kv) for kv in caches]
        return Tensor(toks.T), Tensor(n_emit), Tensor(ok), new_caches

    # -------------------------------------------------------- generation
    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, top_p=1.0, eos_token_id=None, use_jit=False,
                 seed=None):
        """Greedy/sampled decode with KV cache.

        use_jit=True compiles prefill + the full decode loop + sampling
        into ONE XLA program over a fixed-size cache
        (models/generation.py jit_generate — the TPU-native serving
        path); the default eager loop re-dispatches per step."""
        if use_jit:
            from .generation import jit_generate
            return jit_generate(self, input_ids,
                                max_new_tokens=max_new_tokens,
                                temperature=temperature, top_k=top_k,
                                top_p=top_p, eos_token_id=eos_token_id,
                                seed=seed)
        from ..core.autograd import no_grad
        from ..framework.random import rng_key
        from .generation import _sample_arr
        with no_grad():
            b, s = input_ids.shape
            key = (jax.random.PRNGKey(seed) if seed is not None
                   else rng_key())
            caches = [(Tensor(jnp.zeros((b, 0, l.self_attn.n_kv,
                                         l.self_attn.head_dim), jnp.float32)),
                       Tensor(jnp.zeros((b, 0, l.self_attn.n_kv,
                                         l.self_attn.head_dim), jnp.float32)))
                      for l in self.model.layers]
            logits, caches = self.forward(input_ids, caches=caches)
            out_ids = [input_ids]
            import numpy as _np
            done = _np.zeros((b,), bool)
            for _ in range(max_new_tokens):
                last = logits._data[:, -1, :]  # stays on device
                key, kn = jax.random.split(key)
                nxt_arr = _sample_arr(last, kn, float(temperature),
                                      int(top_k), float(top_p))
                if eos_token_id is not None:
                    nxt_arr = jnp.where(jnp.asarray(done),
                                        jnp.int32(eos_token_id), nxt_arr)
                    done = _np.asarray(
                        jnp.logical_or(jnp.asarray(done),
                                       nxt_arr == eos_token_id))
                nxt = Tensor(nxt_arr.astype(input_ids._data.dtype)[:, None])
                out_ids.append(nxt)
                if eos_token_id is not None and done.all():
                    pad = Tensor(jnp.full(
                        (b, max_new_tokens - len(out_ids) + 1),
                        eos_token_id, input_ids._data.dtype))
                    if pad.shape[1] > 0:
                        out_ids.append(pad)
                    break
                logits, caches = self.forward(nxt, caches=caches)
            return M.concat(out_ids, axis=1)


# ------------------------------------------------------------------ pipeline
class _LlamaStage(nn.Layer):
    """One pipeline chunk: `n_layers` consecutive decoder layers."""

    def __init__(self, config: LlamaConfig, n_layers: int):
        super().__init__()
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(n_layers)])

    def forward(self, x, cos, sin):
        for layer in self.layers:
            x = layer(x, cos, sin)
        return x


class LlamaForCausalLMPipe(nn.Layer):
    """Pipeline-parallel Llama with decoder chunks stacked over 'pipe'.

    Parity: the reference expresses pipelined models as a `PipelineLayer`
    of LayerDescs segmented across stages and scheduled by
    `PipelineParallel.forward_backward_pipeline` (1F1B,
    `fleet/meta_parallel/pipeline_parallel.py:565`) or
    `PipelineParallelWithInterleave` (`:1161`), moving activations with
    NCCL p2p per micro-step.

    TPU-native: embedding / final norm / LM head are replicated over the
    pipe axis (sharded over model/data as usual); the homogeneous decoder
    stack is partitioned into `num_stages * n_virtual` chunks whose
    parameters are stacked into (n_virtual, num_stages, ...) arrays sharded
    over 'pipe', and the whole micro-batch schedule runs as one compiled
    lax.scan with ppermute edges (distributed.pipeline.pipeline_forward).
    jax AD derives the reverse pipeline; jax.checkpoint bounds activation
    memory the way 1F1B does. TP composes: the shard_map is manual only on
    'pipe', so GSPMD still shards the mpu layers inside each stage.
    """

    def __init__(self, config: LlamaConfig, num_stages: int = 2,
                 num_microbatches: int = 2, n_virtual: int = 1):
        super().__init__()
        self.cfg = config
        self.num_stages = int(num_stages)
        self.num_microbatches = int(num_microbatches)
        self.n_virtual = int(n_virtual)
        n_chunks = self.num_stages * self.n_virtual
        if config.num_hidden_layers % n_chunks != 0:
            raise ValueError(
                f"num_hidden_layers ({config.num_hidden_layers}) must divide "
                f"into num_stages*n_virtual ({n_chunks}) chunks")
        self.layers_per_chunk = config.num_hidden_layers // n_chunks

        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_cache(head_dim, config.max_position_embeddings,
                               config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

        # Stage template (held out of sublayer registration: its params are
        # placeholders rebound functionally with per-chunk slices).
        tmpl = _LlamaStage(config, self.layers_per_chunk)
        self._tmpl = [tmpl]
        tmpl_sd = tmpl.state_dict()
        self._stage_keys = list(tmpl_sd.keys())

        # Build each chunk with its own init randomness and stack:
        # leaf -> (n_virtual, num_stages, *shape), sharded P(None,'pipe',...)
        stacks = {k: [] for k in self._stage_keys}
        for _ in range(n_chunks):
            blk = _LlamaStage(config, self.layers_per_chunk)
            sd = blk.state_dict()
            for k in self._stage_keys:
                stacks[k].append(sd[k]._data)
        for k in self._stage_keys:
            arr = jnp.stack(stacks[k], axis=0)
            arr = arr.reshape(self.n_virtual, self.num_stages, *arr.shape[1:])
            p = Tensor(arr, stop_gradient=False)
            p._is_param = True
            base_spec = getattr(tmpl_sd[k], "_spec", None)
            tail = tuple(base_spec) if base_spec is not None else \
                tuple([None] * (arr.ndim - 2))
            self.add_parameter(self._pname(k), p)
            mark_sharding(p, P(None, "pipe", *tail))

    @staticmethod
    def _pname(key):
        return "pipe_stages__" + key.replace(".", "__")

    @classmethod
    def from_causal_lm(cls, model: "LlamaForCausalLM", num_stages: int = 2,
                       num_microbatches: int = 2, n_virtual: int = 1):
        """Build a pipelined model carrying `model`'s weights (chunk c holds
        decoder layers [c*L/C, (c+1)*L/C) at ring pass c // num_stages,
        device c % num_stages)."""
        pipe = cls(model.cfg, num_stages=num_stages,
                   num_microbatches=num_microbatches, n_virtual=n_virtual)
        pipe.embed_tokens.weight.set_value(model.model.embed_tokens.weight)
        pipe.norm.weight.set_value(model.model.norm.weight)
        if pipe.lm_head is not None:
            pipe.lm_head.weight.set_value(model.lm_head.weight)
        plain_sd = model.state_dict()
        n_chunks = pipe.num_stages * pipe.n_virtual
        for k in pipe._stage_keys:
            # template key: "layers.<j>.<suffix>"
            _, j, suffix = k.split(".", 2)
            leaf = pipe._parameters[pipe._pname(k)]
            arr = leaf._data
            for c in range(n_chunks):
                i = c * pipe.layers_per_chunk + int(j)
                v, d = divmod(c, pipe.num_stages)
                src = plain_sd[f"model.layers.{i}.{suffix}"]._data
                arr = arr.at[v, d].set(src.astype(arr.dtype))
            leaf._data = arr
        return pipe

    def forward(self, input_ids, labels=None):
        from ..distributed.fleet.mpu import current_mesh
        from ..distributed.pipeline import pipeline_forward
        from ..jit.api import functional_call
        from ..kernels.flash_attention import _interpret_mode
        from ..nn.functional.flash_attention import sdp_kernel

        cfg = self.cfg
        b, s = input_ids.shape
        cos = apply_op("rope_slice", lambda c: c[:s], self.rope_cos)
        sin = apply_op("rope_slice", lambda c: c[:s], self.rope_sin)
        x = self.embed_tokens(input_ids)
        if cfg.sequence_parallel:
            x = apply_op("sp_shard",
                         lambda a: _constraint(a, P("data", "sep", None)), x)

        tmpl = self._tmpl[0]
        keys = self._stage_keys
        leaves = [self._parameters[self._pname(k)] for k in keys]
        mesh = current_mesh()
        use_pipe = (mesh is not None and "pipe" in mesh.shape
                    and mesh.shape["pipe"] == self.num_stages
                    and self.num_stages > 1)
        # interpret-mode pallas calls can't be replayed by remat; real TPU
        # keeps the flash kernel inside the checkpointed stage.
        use_flash = not _interpret_mode()

        def stage_raw(params, xx, cc, ss):
            with sdp_kernel(enable_flash=use_flash):
                return functional_call(tmpl, {k: v for k, v in params.items()},
                                       Tensor(xx), Tensor(cc), Tensor(ss))._data

        if use_pipe:
            n_micro = self.num_microbatches
            if b % n_micro != 0:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {n_micro}")

            def pipe_raw(*arrs):
                pl, (xx, cc, ss) = arrs[:len(keys)], arrs[len(keys):]
                params = dict(zip(keys, pl))
                if self.n_virtual == 1:
                    params = {k: a[0] for k, a in params.items()}
                micro = xx.reshape(n_micro, b // n_micro, *xx.shape[1:])
                out = pipeline_forward(
                    params, micro,
                    lambda p, xm, cc_, ss_: stage_raw(p, xm, cc_, ss_),
                    mesh, extras=(cc, ss), n_virtual=self.n_virtual,
                    remat=True)
                return out.reshape(b, *out.shape[2:])

            x = apply_op("llama_pipeline", pipe_raw, *leaves, x, cos, sin)
        else:
            # No live pipe mesh: run chunks sequentially (same math).
            def seq_raw(*arrs):
                pl, (xx, cc, ss) = arrs[:len(keys)], arrs[len(keys):]
                y = xx
                for v in range(self.n_virtual):
                    for d in range(self.num_stages):
                        pv = {k: a[v, d] for k, a in zip(keys, pl)}
                        y = stage_raw(pv, y, cc, ss)
                return y

            x = apply_op("llama_pipeline_seq", seq_raw, *leaves, x, cos, sin)

        x = self.norm(x)
        tied = self.embed_tokens.weight if self.lm_head is None else None
        return _head_and_loss(x, labels, self.lm_head, tied)
