"""Llama-family decoder LM — the flagship pretraining model.

Capability parity: the reference trains Llama via PaddleNLP recipes on top
of fleet hybrid parallel (SURVEY.md §3.3); this module provides the model +
hybrid-parallel training step natively.

TPU-first design:
  * weights carry GSPMD shardings over the hybrid mesh axes
    ([data, pipe, sharding, sep, model]) via the fleet.mpu layers —
    ColumnParallel/RowParallel/VocabParallel place qkv/mlp/vocab exactly as
    Megatron-TP does, and XLA inserts the ICI collectives;
  * attention runs through nn.functional.scaled_dot_product_attention
    (Pallas flash kernel when eligible);
  * sequence parallelism = Shard over the 'sep' axis on the seq dim of
    activations (Ulysses-style alltoall emitted by GSPMD at the attention
    boundary);
  * the training step is compiled end-to-end with jit (fwd+bwd+AdamW).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mpu import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding, _constraint)
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.dispatch import apply_op
from jax.sharding import PartitionSpec as P

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_tiny",
           "llama_3_8b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_bias: bool = False
    sequence_parallel: bool = False
    recompute: bool = False
    dtype: str = "float32"


def llama_tiny(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=128)
    cfg.update(kw)
    return LlamaConfig(**cfg)


def llama_3_8b(**kw):
    cfg = dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
               num_hidden_layers=32, num_attention_heads=32,
               num_key_value_heads=8, max_position_embeddings=8192,
               rope_theta=500000.0)
    cfg.update(kw)
    return LlamaConfig(**cfg)


def _rope_cache(head_dim, max_pos, theta, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # (S, D/2)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x, cos, sin):
    """x: (B, S, H, D). Rotates pairs (even, odd) — NeoX/Llama convention."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1)
    return out.reshape(x.shape)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        self.head_dim = h // config.num_attention_heads
        self.n_heads = config.num_attention_heads
        self.n_kv = config.num_key_value_heads
        self.q_proj = ColumnParallelLinear(h, h, has_bias=config.use_bias,
                                           gather_output=False)
        self.k_proj = ColumnParallelLinear(h, self.n_kv * self.head_dim,
                                           has_bias=config.use_bias,
                                           gather_output=False)
        self.v_proj = ColumnParallelLinear(h, self.n_kv * self.head_dim,
                                           has_bias=config.use_bias,
                                           gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=config.use_bias,
                                        input_is_parallel=True)

    def forward(self, x, cos, sin, cache=None):
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.n_heads, self.head_dim])
        k = M.reshape(self.k_proj(x), [b, s, self.n_kv, self.head_dim])
        v = M.reshape(self.v_proj(x), [b, s, self.n_kv, self.head_dim])
        q = apply_op("rope", apply_rotary, q, cos, sin)
        k = apply_op("rope", apply_rotary, k, cos, sin)
        if cache is not None:
            pk, pv = cache
            k = M.concat([pk, k], axis=1)
            v = M.concat([pv, v], axis=1)
            cache = (k, v)
        if self.n_kv != self.n_heads:
            rep = self.n_heads // self.n_kv
            k = apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2), k)
            v = apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2), v)
        # causal whenever we score more than one query position (prefill with
        # a cache included); single-token decode needs no mask. The sdpa
        # causal mask is key-offset-aware (tril with k=sk-sq).
        out = F.scaled_dot_product_attention(q, k, v, is_causal=(s > 1))
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        out = self.o_proj(out)
        return (out, cache) if cache is not None else out


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, i, has_bias=config.use_bias,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, i, has_bias=config.use_bias,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(i, h, has_bias=config.use_bias,
                                           input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cos, sin, cache=None):
        h = self.input_layernorm(x)
        if cache is not None:
            attn, cache = self.self_attn(h, cos, sin, cache)
        else:
            attn = self.self_attn(h, cos, sin)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return (x, cache) if cache is not None else x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_cache(head_dim, config.max_position_embeddings,
                               config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, caches=None):
        s = input_ids.shape[1]
        past = caches[0][0].shape[1] if caches is not None else 0
        cos = apply_op("rope_slice",
                       lambda c: jax.lax.dynamic_slice_in_dim(c, past, s, 0),
                       self.rope_cos)
        sin = apply_op("rope_slice",
                       lambda c: jax.lax.dynamic_slice_in_dim(c, past, s, 0),
                       self.rope_sin)
        x = self.embed_tokens(input_ids)
        if self.cfg.sequence_parallel:
            x = apply_op("sp_shard",
                         lambda a: _constraint(a, P("data", "sep", None)), x)
        new_caches = []
        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, cos, sin, caches[i])
                new_caches.append(c)
            elif self.cfg.recompute:
                x = _recompute_layer(layer, x, cos, sin)
            else:
                x = layer(x, cos, sin)
        x = self.norm(x)
        return (x, new_caches) if caches is not None else x


def _recompute_layer(layer, x, cos, sin):
    """Activation checkpointing via jax.checkpoint over the layer's pure fn
    (parity: fleet/recompute/recompute.py RecomputeFunction)."""
    from ..jit.api import functional_call
    from ..kernels.flash_attention import _interpret_mode
    from ..nn.functional.flash_attention import sdp_kernel
    sd = layer.state_dict()
    keys = list(sd)
    # interpret-mode pallas calls can't be replayed by remat; real TPU keeps
    # the flash kernel inside the checkpointed region.
    use_flash = not _interpret_mode()

    def pure(params, xx, cc, ss):
        with sdp_kernel(enable_flash=use_flash):
            return functional_call(layer, dict(zip(keys, params)),
                                   Tensor(xx), Tensor(cc), Tensor(ss))._data

    ck = jax.checkpoint(pure, static_argnums=())
    return apply_op("recompute_layer",
                    lambda *arrs: ck(list(arrs[:len(keys)]), *arrs[len(keys):]),
                    *[sd[k] for k in keys], x, cos, sin)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)

    def forward(self, input_ids, labels=None, caches=None):
        if caches is not None:
            h, caches = self.model(input_ids, caches)
        else:
            h = self.model(input_ids)
        if self.lm_head is None:
            w = self.model.embed_tokens.weight
            logits = apply_op("tied_head", lambda a, ww: a @ ww.T, h, w)
        else:
            logits = self.lm_head(h)
        if labels is not None:
            from ..distributed.fleet.mpu import ParallelCrossEntropy
            # next-token objective: logits[:, :-1] predict labels[:, 1:]
            shift_logits = apply_op("shift", lambda a: a[:, :-1, :], logits)
            shift_labels = apply_op("shift", lambda a: a[:, 1:], labels)
            loss_t = ParallelCrossEntropy()(shift_logits, shift_labels)
            # masked mean over valid (non-ignore_index) positions
            def _masked_mean(l, lab):
                valid = (lab != -100).astype(l.dtype)
                return jnp.sum(l[..., 0] * valid) / jnp.maximum(jnp.sum(valid), 1.0)
            loss = apply_op("masked_mean", _masked_mean, loss_t, shift_labels)
            return loss
        return (logits, caches) if caches is not None else logits

    # -------------------------------------------------------- generation
    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_token_id=None):
        """Greedy/sampled decode with KV cache (eager loop)."""
        from ..core.autograd import no_grad
        from ..framework.random import rng_key
        with no_grad():
            b, s = input_ids.shape
            caches = [(Tensor(jnp.zeros((b, 0, l.self_attn.n_kv,
                                         l.self_attn.head_dim), jnp.float32)),
                       Tensor(jnp.zeros((b, 0, l.self_attn.n_kv,
                                         l.self_attn.head_dim), jnp.float32)))
                      for l in self.model.layers]
            logits, caches = self.forward(input_ids, caches=caches)
            out_ids = [input_ids]
            for _ in range(max_new_tokens):
                last = logits._data[:, -1, :]  # stays on device
                if temperature > 0:
                    nxt = Tensor(jax.random.categorical(
                        rng_key(), last / temperature)[:, None])
                else:
                    nxt = Tensor(jnp.argmax(last, axis=-1)[:, None])
                out_ids.append(nxt)
                logits, caches = self.forward(nxt, caches=caches)
            return M.concat(out_ids, axis=1)
