"""Model zoo: the config-ladder families (BASELINE.md).

ResNet/VGG/MobileNet live in paddle_tpu.vision.models; this package holds
the LLM/diffusion families.
"""
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM, llama_tiny, llama_3_8b  # noqa: F401
from .ernie import (ErnieConfig, ErnieModel, ErnieForMaskedLM,  # noqa: F401
                    ErnieForPretraining, ErnieForSequenceClassification,
                    ErnieForTokenClassification, ernie_tiny, ernie_3_base)
from .dit import (DiTConfig, DiT, GaussianDiffusion, dit_tiny,  # noqa: F401
                  dit_s_2, dit_xl_2)
from .unet import UNetConfig, UNet2DModel, unet_tiny  # noqa: F401
from .generation import jit_generate  # noqa: F401
from .qwen2_moe import (Qwen2MoeConfig, Qwen2MoeForCausalLM,  # noqa: F401
                        qwen2_moe_tiny, qwen2_moe_a14b)
