"""Model zoo: the config-ladder families (BASELINE.md).

ResNet/VGG/MobileNet live in paddle_tpu.vision.models; this package holds
the LLM/diffusion families.
"""
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM, llama_tiny, llama_3_8b  # noqa: F401
