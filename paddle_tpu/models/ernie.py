"""ERNIE-style bidirectional encoder (BERT architecture) — the finetune
rung of the config ladder (BASELINE.md: "ERNIE-3.0 finetune").

Capability parity: the reference serves ERNIE through PaddleNLP on top of
`paddle.nn.TransformerEncoder` (reference
`python/paddle/nn/layer/transformer.py`) with fleet TP when sharded; this
module provides the model natively with the same TP-sharded mpu layers as
the Llama family (`fleet/layers/mpu/mp_layers.py` parity), so qkv/ffn
columns/rows and the vocab embedding shard over the 'model' mesh axis and
XLA emits the ICI collectives.

Heads: masked-LM, pretraining (MLM+NSP), sequence/token classification —
the PaddleNLP head surface a finetune user needs.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mpu import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding)
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.dispatch import apply_op

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForMaskedLM",
           "ErnieForPretraining", "ErnieForSequenceClassification",
           "ErnieForTokenClassification", "ernie_tiny", "ernie_3_base"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    dtype: str = "float32"


def ernie_tiny(**kw):
    cfg = dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, intermediate_size=128,
               max_position_embeddings=128, type_vocab_size=2)
    cfg.update(kw)
    return ErnieConfig(**cfg)


def ernie_3_base(**kw):
    """ERNIE 3.0 base scale (12L/768H)."""
    cfg = dict(vocab_size=40000, hidden_size=768, num_hidden_layers=12,
               num_attention_heads=12, intermediate_size=3072)
    cfg.update(kw)
    return ErnieConfig(**cfg)


class ErnieEmbeddings(nn.Layer):
    """word + position + token_type embeddings, LN, dropout. The word
    table is vocab-parallel over the 'model' axis."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(cfg.vocab_size,
                                                      cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = apply_op(
                "pos_ids",
                lambda ids: jnp.broadcast_to(
                    jnp.arange(s, dtype=jnp.int32)[None, :], (b, s)),
                input_ids)
        if token_type_ids is None:
            token_type_ids = apply_op(
                "tt_ids", lambda ids: jnp.zeros_like(ids), input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class ErnieSelfAttention(nn.Layer):
    """TP-sharded bidirectional attention (flash kernel when eligible)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        h = cfg.hidden_size
        self.n_heads = cfg.num_attention_heads
        self.head_dim = h // cfg.num_attention_heads
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(h, h, has_bias=True,
                                          input_is_parallel=True)
        self.dropout_p = cfg.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        b, s, _ = x.shape
        qkv = M.reshape(self.qkv_proj(x), [b, s, 3, self.n_heads,
                                           self.head_dim])
        q = apply_op("qkv_split", lambda a: a[:, :, 0], qkv)
        k = apply_op("qkv_split", lambda a: a[:, :, 1], qkv)
        v = apply_op("qkv_split", lambda a: a[:, :, 2], qkv)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout_p,
            is_causal=False, training=self.training)
        out = M.reshape(out, [b, s, self.n_heads * self.head_dim])
        return self.out_proj(out)


class ErnieEncoderLayer(nn.Layer):
    """Post-LN transformer block (BERT convention, matching the
    reference's TransformerEncoderLayer normalize_before=False default,
    `python/paddle/nn/layer/transformer.py:82`)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        h, i = cfg.hidden_size, cfg.intermediate_size
        self.self_attn = ErnieSelfAttention(cfg)
        self.norm1 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.linear1 = ColumnParallelLinear(h, i, has_bias=True,
                                            gather_output=False)
        self.linear2 = RowParallelLinear(i, h, has_bias=True,
                                         input_is_parallel=True)
        self.norm2 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.norm1(x + self.dropout(self.self_attn(x, attn_mask)))
        ff = self.linear2(F.gelu(self.linear1(x)))
        return self.norm2(x + self.dropout(ff))


class ErniePooler(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        first = apply_op("cls_token", lambda a: a[:, 0], hidden)
        return F.tanh(self.dense(first))


def _extend_attention_mask(input_ids, attention_mask, pad_token_id):
    """(B,S) 1/0 mask (or pad-id inference) -> additive (B,1,S,S) bias."""
    def _f(ids, m):
        keep = m.astype(jnp.float32) if m is not None \
            else (ids != pad_token_id).astype(jnp.float32)
        bias = (1.0 - keep)[:, None, None, :] * jnp.finfo(jnp.float32).min
        return jnp.broadcast_to(bias, (ids.shape[0], 1, ids.shape[1],
                                       ids.shape[1]))
    if attention_mask is None:
        return apply_op("attn_mask", lambda ids: _f(ids, None), input_ids)
    return apply_op("attn_mask", _f, input_ids, attention_mask)


class ErnieModel(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        self.encoder = nn.LayerList([ErnieEncoderLayer(cfg)
                                     for _ in range(cfg.num_hidden_layers)])
        self.pooler = ErniePooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        mask = _extend_attention_mask(input_ids, attention_mask,
                                      self.cfg.pad_token_id)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, mask)
        return x, self.pooler(x)


class _MLMHead(nn.Layer):
    """transform + LN + tied/untied vocab projection."""

    def __init__(self, cfg: ErnieConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.decoder_weight = embedding_weights
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True,
            default_initializer=nn.initializer.Constant(0.0))

    def forward(self, hidden):
        h = self.layer_norm(F.gelu(self.transform(hidden)))
        return apply_op("mlm_logits",
                        lambda a, w, b: a @ w.T + b,
                        h, self.decoder_weight, self.decoder_bias)


class ErnieForMaskedLM(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.cls = _MLMHead(cfg, self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        hidden, _ = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask)
        logits = self.cls(hidden)
        if labels is None:
            return logits
        return _masked_ce(logits, labels)


class ErnieForPretraining(nn.Layer):
    """MLM + next-sentence-prediction joint objective."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.cls = _MLMHead(cfg, self.ernie.embeddings.word_embeddings.weight)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None, next_sentence_label=None):
        hidden, pooled = self.ernie(input_ids, token_type_ids,
                                    attention_mask=attention_mask)
        mlm_logits = self.cls(hidden)
        nsp_logits = self.nsp(pooled)
        if labels is None:
            return mlm_logits, nsp_logits
        loss = _masked_ce(mlm_logits, labels)
        if next_sentence_label is not None:
            loss = loss + F.cross_entropy(
                nsp_logits, next_sentence_label, reduction="mean")
        return loss


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels, reduction="mean")


class ErnieForTokenClassification(nn.Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        hidden, _ = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask)
        logits = self.classifier(self.dropout(hidden))
        if labels is None:
            return logits
        return _masked_ce(logits, labels)


def _masked_ce(logits, labels, ignore_index=-100):
    """mean CE over positions where label != ignore_index."""
    def _f(lg, lab):
        v = lg.reshape(-1, lg.shape[-1])
        t = lab.reshape(-1)
        valid = (t != ignore_index)
        safe_t = jnp.where(valid, t, 0)
        logp = v - _lse(v)
        nll = -jnp.take_along_axis(logp, safe_t[:, None], axis=-1)[:, 0]
        vf = valid.astype(v.dtype)
        return jnp.sum(nll * vf) / jnp.maximum(jnp.sum(vf), 1.0)
    return apply_op("masked_ce", _f, logits, labels)


def _lse(v):
    m = jnp.max(v, axis=-1, keepdims=True)
    return m + jnp.log(jnp.sum(jnp.exp(v - m), axis=-1, keepdims=True))
