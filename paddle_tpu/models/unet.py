"""Time-conditioned UNet denoiser — the Stable-Diffusion-style conv rung
of the model ladder (BASELINE.md configs: "SD/DiT mixed conv+attn").

Capability parity: the reference trains SD/LDM-class UNets through
PaddleMIX on the same core ops (conv + attention + group norm); this is a
native implementation of that architecture class: ResBlocks with
scale-shift time conditioning, down/up paths with skip concat, and
self-attention at the low-resolution levels. Shares `GaussianDiffusion`
(models/dit.py) for DDPM training and DDIM sampling.

TPU notes: NCHW layout at the API (paddle convention) with XLA choosing
the device layout; attention runs through the framework's
scaled_dot_product_attention so the Pallas flash path engages when shapes
are eligible; everything is static-shaped and jit/to_static friendly.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..ops.dispatch import apply_op
from ..ops.manipulation import concat
from .dit import GaussianDiffusion, TimestepEmbedder  # noqa: F401

__all__ = ["UNetConfig", "UNet2DModel", "unet_tiny", "GaussianDiffusion"]


@dataclass
class UNetConfig:
    in_channels: int = 3
    out_channels: int = 3
    base_channels: int = 64
    channel_mults: tuple = (1, 2, 4)
    num_res_blocks: int = 2
    attn_levels: tuple = (2,)        # indices into channel_mults
    num_heads: int = 4
    groups: int = 8
    dropout: float = 0.0
    num_classes: int = 0             # >0 enables class conditioning via y
    learn_sigma: bool = False        # GaussianDiffusion splits eps if True


class _ResBlock(nn.Layer):
    """GroupNorm -> SiLU -> conv, with scale-shift time conditioning
    (the SD UNet block shape)."""

    def __init__(self, inp, out, t_dim, groups, dropout):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, inp)
        self.conv1 = nn.Conv2D(inp, out, 3, padding=1)
        self.t_proj = nn.Linear(t_dim, out * 2)
        self.norm2 = nn.GroupNorm(groups, out)
        self.drop = nn.Dropout(dropout)
        self.conv2 = nn.Conv2D(out, out, 3, padding=1)
        self.act = nn.Silu()
        self.skip = nn.Conv2D(inp, out, 1) if inp != out else None

    def forward(self, x, temb):
        h = self.conv1(self.act(self.norm1(x)))
        ss = self.t_proj(self.act(temb))

        def _cond(hh, s):
            scale, shift = jnp.split(s[:, :, None, None], 2, axis=1)
            return hh * (1 + scale) + shift

        h = apply_op("unet_scale_shift", _cond, self.norm2(h), ss)
        h = self.conv2(self.drop(self.act(h)))
        base = self.skip(x) if self.skip is not None else x
        return base + h


class _SelfAttention2D(nn.Layer):
    """Spatial self-attention over HxW tokens (flash-eligible)."""

    def __init__(self, channels, num_heads, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels)
        self.qkv = nn.Linear(channels, channels * 3)
        self.proj = nn.Linear(channels, channels)
        self.heads = num_heads

    def forward(self, x):
        B, C, H, W = x.shape
        h = self.norm(x)

        def _to_tokens(a):
            return jnp.transpose(a.reshape(a.shape[0], a.shape[1], -1),
                                 (0, 2, 1))
        tok = apply_op("unet_to_tokens", _to_tokens, h)     # (B, HW, C)
        qkv = self.qkv(tok)
        from ..nn.functional import scaled_dot_product_attention

        def _split_heads(a):
            b, s, _ = a.shape
            return a.reshape(b, s, 3, self.heads,
                             a.shape[-1] // (3 * self.heads))
        qkv = apply_op("unet_split_heads", _split_heads, qkv)
        q, k, v = (apply_op("unet_pick", lambda a, i=i: a[:, :, i], qkv)
                   for i in range(3))
        att = scaled_dot_product_attention(q, k, v)
        att = apply_op("unet_merge_heads",
                       lambda a: a.reshape(a.shape[0], a.shape[1], -1), att)
        out = self.proj(att)

        def _to_map(a):
            return jnp.transpose(a, (0, 2, 1)).reshape(B, C, H, W)
        return x + apply_op("unet_to_map", _to_map, out)


class _Down(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.op = nn.Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.op(x)


class _Up(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.up = nn.Upsample(scale_factor=2, mode="nearest")
        self.op = nn.Conv2D(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.op(self.up(x))


class UNet2DModel(nn.Layer):
    """epsilon-prediction UNet: forward(x_t (B,C,H,W), t (B,), y=None)."""

    def __init__(self, cfg: UNetConfig = None, **kw):
        super().__init__()
        self.cfg = cfg or UNetConfig(**kw)
        c = self.cfg
        t_dim = c.base_channels * 4
        self.t_embed = TimestepEmbedder(t_dim)
        self.y_embed = (nn.Embedding(c.num_classes, t_dim)
                        if c.num_classes > 0 else None)
        self.conv_in = nn.Conv2D(c.in_channels, c.base_channels, 3,
                                 padding=1)

        downs, ch, skips = [], c.base_channels, [c.base_channels]
        for lvl, mult in enumerate(c.channel_mults):
            out = c.base_channels * mult
            for _ in range(c.num_res_blocks):
                blk = [_ResBlock(ch, out, t_dim, c.groups, c.dropout)]
                if lvl in c.attn_levels:
                    blk.append(_SelfAttention2D(out, c.num_heads, c.groups))
                downs.append(nn.LayerList(blk))
                ch = out
                skips.append(ch)
            if lvl != len(c.channel_mults) - 1:
                downs.append(nn.LayerList([_Down(ch)]))
                skips.append(ch)
        self.downs = nn.LayerList(downs)
        self._skip_chs = skips

        self.mid1 = _ResBlock(ch, ch, t_dim, c.groups, c.dropout)
        self.mid_attn = _SelfAttention2D(ch, c.num_heads, c.groups)
        self.mid2 = _ResBlock(ch, ch, t_dim, c.groups, c.dropout)

        ups = []
        skip_stack = list(skips)
        for lvl in reversed(range(len(c.channel_mults))):
            out = c.base_channels * c.channel_mults[lvl]
            for _ in range(c.num_res_blocks + 1):
                sk = skip_stack.pop()
                blk = [_ResBlock(ch + sk, out, t_dim, c.groups, c.dropout)]
                if lvl in c.attn_levels:
                    blk.append(_SelfAttention2D(out, c.num_heads, c.groups))
                ups.append(nn.LayerList(blk))
                ch = out
            if lvl != 0:
                ups.append(nn.LayerList([_Up(ch)]))
        self.ups = nn.LayerList(ups)

        self.norm_out = nn.GroupNorm(c.groups, ch)
        self.act = nn.Silu()
        out_ch = c.out_channels * (2 if c.learn_sigma else 1)
        self.conv_out = nn.Conv2D(ch, out_ch, 3, padding=1)

    def forward(self, x, t, y=None):
        temb = self.t_embed(t)
        if y is not None:
            if self.y_embed is None:
                raise ValueError(
                    "labels passed but UNetConfig.num_classes == 0 — this "
                    "UNet is unconditional")
            temb = temb + self.y_embed(y)
        h = self.conv_in(x)
        hs = [h]
        for blk in self.downs:
            mods = list(blk)
            if isinstance(mods[0], _Down):
                h = mods[0](h)
            else:
                h = mods[0](h, temb)
                if len(mods) > 1:
                    h = mods[1](h)
            hs.append(h)
        h = self.mid2(self.mid_attn(self.mid1(h, temb)), temb)
        for blk in self.ups:
            mods = list(blk)
            if isinstance(mods[0], _Up):
                h = mods[0](h)
            else:
                h = mods[0](concat([h, hs.pop()], axis=1), temb)
                if len(mods) > 1:
                    h = mods[1](h)
        return self.conv_out(self.act(self.norm_out(h)))


def unet_tiny(**kw):
    kw.setdefault("base_channels", 32)
    kw.setdefault("channel_mults", (1, 2))
    kw.setdefault("num_res_blocks", 1)
    kw.setdefault("attn_levels", (1,))
    return UNet2DModel(UNetConfig(**kw))
