"""DiT — diffusion transformer + DDPM/DDIM pipeline (the "SD/DiT" rung of
the config ladder, BASELINE.md: mixed conv+attention workload).

Capability parity: the reference serves Stable Diffusion/DiT through
PaddleMIX on `paddle.nn` conv/attention layers; this module provides the
DiT architecture (Peebles & Xie 2023: patchify -> adaLN-Zero transformer
blocks conditioned on timestep+class -> unpatchify) and a minimal
DDPM/DDIM trainer/sampler natively.

TPU-first notes: patchify is a conv with stride=patch (one MXU matmul per
patch row); adaLN modulation fuses into the surrounding elementwise ops
under XLA; the sampler loop is jittable per-step (static shapes).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.dispatch import apply_op

__all__ = ["DiTConfig", "DiT", "GaussianDiffusion", "dit_tiny", "dit_s_2",
           "dit_xl_2"]


@dataclass
class DiTConfig:
    image_size: int = 32          # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    depth: int = 28
    num_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000      # 0 => unconditional
    learn_sigma: bool = True
    class_dropout_prob: float = 0.1


def dit_tiny(**kw):
    cfg = dict(image_size=8, patch_size=2, in_channels=3, hidden_size=64,
               depth=2, num_heads=4, num_classes=10, learn_sigma=False)
    cfg.update(kw)
    return DiTConfig(**cfg)


def dit_s_2(**kw):
    cfg = dict(patch_size=2, hidden_size=384, depth=12, num_heads=6)
    cfg.update(kw)
    return DiTConfig(**cfg)


def dit_xl_2(**kw):
    cfg = dict(patch_size=2, hidden_size=1152, depth=28, num_heads=16)
    cfg.update(kw)
    return DiTConfig(**cfg)


class TimestepEmbedder(nn.Layer):
    """Sinusoidal timestep embedding -> 2-layer MLP (DiT convention)."""

    def __init__(self, hidden_size, freq_dim=256):
        super().__init__()
        self.freq_dim = freq_dim
        self.mlp = nn.Sequential(
            nn.Linear(freq_dim, hidden_size), nn.Silu(),
            nn.Linear(hidden_size, hidden_size))

    def forward(self, t):
        def _sincos(tt):
            half = self.freq_dim // 2
            freqs = jnp.exp(-math.log(10000.0)
                            * jnp.arange(half, dtype=jnp.float32) / half)
            args = tt.astype(jnp.float32)[:, None] * freqs[None, :]
            return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
        emb = apply_op("t_embed", _sincos, t)
        return self.mlp(emb)


class LabelEmbedder(nn.Layer):
    """Class label -> embedding, with CFG dropout to the null class."""

    def __init__(self, num_classes, hidden_size, dropout_prob):
        super().__init__()
        self.num_classes = num_classes
        self.dropout_prob = dropout_prob
        self.table = nn.Embedding(num_classes + 1, hidden_size)

    def forward(self, labels, train: bool):
        if train and self.dropout_prob > 0:
            from ..framework.random import rng_key
            def _drop(lab):
                key = rng_key()
                drop = jax.random.bernoulli(key, self.dropout_prob,
                                            lab.shape)
                return jnp.where(drop, self.num_classes, lab)
            labels = apply_op("cfg_drop", _drop, labels)
        return self.table(labels)


def _modulate(x, shift, scale):
    return apply_op("modulate",
                    lambda a, sh, sc: a * (1 + sc[:, None, :])
                    + sh[:, None, :], x, shift, scale)


class DiTBlock(nn.Layer):
    """adaLN-Zero transformer block: LN(no affine) -> modulate -> attn/mlp,
    gated residuals initialised at zero."""

    def __init__(self, cfg: DiTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.norm1 = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        self.attn_qkv = nn.Linear(h, 3 * h)
        self.attn_out = nn.Linear(h, h)
        self.norm2 = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        mlp_h = int(h * cfg.mlp_ratio)
        self.mlp_fc1 = nn.Linear(h, mlp_h)
        self.mlp_fc2 = nn.Linear(mlp_h, h)
        self.n_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        # adaLN: 6 modulation vectors from the conditioning embedding;
        # zero-init so each block starts as identity (adaLN-Zero)
        zero = nn.ParamAttr(initializer=nn.initializer.Constant(0.0))
        self.adaLN = nn.Linear(h, 6 * h, weight_attr=zero, bias_attr=zero)

    def forward(self, x, c):
        b, s, h = x.shape
        mod = self.adaLN(F.silu(c))
        shift_a, scale_a, gate_a, shift_m, scale_m, gate_m = [
            apply_op("chunk", lambda a, i=i: a[:, i * h:(i + 1) * h], mod)
            for i in range(6)]
        # attention
        xa = _modulate(self.norm1(x), shift_a, scale_a)
        qkv = M.reshape(self.attn_qkv(xa), [b, s, 3, self.n_heads,
                                            self.head_dim])
        q = apply_op("q", lambda a: a[:, :, 0], qkv)
        k = apply_op("k", lambda a: a[:, :, 1], qkv)
        v = apply_op("v", lambda a: a[:, :, 2], qkv)
        att = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        att = self.attn_out(M.reshape(att, [b, s, h]))
        x = x + apply_op("gate", lambda g, a: g[:, None, :] * a, gate_a, att)
        # mlp
        xm = _modulate(self.norm2(x), shift_m, scale_m)
        mlp = self.mlp_fc2(F.gelu(self.mlp_fc1(xm), approximate=True))
        x = x + apply_op("gate", lambda g, a: g[:, None, :] * a, gate_m, mlp)
        return x


class FinalLayer(nn.Layer):
    def __init__(self, cfg: DiTConfig, out_channels):
        super().__init__()
        h = cfg.hidden_size
        self.norm = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                 bias_attr=False)
        zero = nn.ParamAttr(initializer=nn.initializer.Constant(0.0))
        self.adaLN = nn.Linear(h, 2 * h, weight_attr=zero, bias_attr=zero)
        self.linear = nn.Linear(
            h, cfg.patch_size * cfg.patch_size * out_channels,
            weight_attr=zero, bias_attr=zero)

    def forward(self, x, c):
        h = x.shape[-1]
        mod = self.adaLN(F.silu(c))
        shift = apply_op("chunk", lambda a: a[:, :h], mod)
        scale = apply_op("chunk", lambda a: a[:, h:], mod)
        return self.linear(_modulate(self.norm(x), shift, scale))


def _pos_embed_2d(dim, grid):
    """Fixed sin-cos 2D positional embedding (DiT uses non-learned)."""
    def _1d(d, pos):
        omega = 1.0 / (10000 ** (jnp.arange(d // 2, dtype=jnp.float32)
                                 / (d / 2.0)))
        out = jnp.outer(pos, omega)
        return jnp.concatenate([jnp.sin(out), jnp.cos(out)], axis=1)
    coords = jnp.arange(grid, dtype=jnp.float32)
    yy, xx = jnp.meshgrid(coords, coords, indexing="ij")
    emb = jnp.concatenate([_1d(dim // 2, yy.reshape(-1)),
                           _1d(dim // 2, xx.reshape(-1))], axis=1)
    return emb  # (grid*grid, dim)


class DiT(nn.Layer):
    def __init__(self, cfg: DiTConfig):
        super().__init__()
        self.cfg = cfg
        self.out_channels = cfg.in_channels * (2 if cfg.learn_sigma else 1)
        self.x_embedder = nn.Conv2D(cfg.in_channels, cfg.hidden_size,
                                    kernel_size=cfg.patch_size,
                                    stride=cfg.patch_size)
        self.t_embedder = TimestepEmbedder(cfg.hidden_size)
        if cfg.num_classes > 0:
            self.y_embedder = LabelEmbedder(cfg.num_classes,
                                            cfg.hidden_size,
                                            cfg.class_dropout_prob)
        else:
            self.y_embedder = None
        grid = cfg.image_size // cfg.patch_size
        self.register_buffer("pos_embed",
                             Tensor(_pos_embed_2d(cfg.hidden_size, grid)),
                             persistable=False)
        self.blocks = nn.LayerList([DiTBlock(cfg) for _ in range(cfg.depth)])
        self.final_layer = FinalLayer(cfg, self.out_channels)

    def unpatchify(self, x):
        cfg = self.cfg
        p = cfg.patch_size
        grid = cfg.image_size // p
        c = self.out_channels

        def _f(a):
            b = a.shape[0]
            a = a.reshape(b, grid, grid, p, p, c)
            a = jnp.einsum("bhwpqc->bchpwq", a)
            return a.reshape(b, c, grid * p, grid * p)
        return apply_op("unpatchify", _f, x)

    def forward(self, x, t, y=None):
        """x: (B, C, H, W) noisy input; t: (B,) timesteps; y: (B,) labels."""
        x = self.x_embedder(x)  # (B, hidden, H/p, W/p)
        x = apply_op("flatten_patches",
                     lambda a: a.reshape(a.shape[0], a.shape[1], -1)
                     .transpose(0, 2, 1), x)
        x = x + self.pos_embed
        c = self.t_embedder(t)
        if self.y_embedder is not None and y is not None:
            c = c + self.y_embedder(y, train=self.training)
        for blk in self.blocks:
            x = blk(x, c)
        x = self.final_layer(x, c)
        return self.unpatchify(x)


# ---------------------------------------------------------------------------
# Diffusion process (DDPM training / DDPM+DDIM sampling)
# ---------------------------------------------------------------------------

class GaussianDiffusion:
    """Linear-beta DDPM; epsilon-prediction objective.

    train_loss(model, x0, y) -> scalar MSE(eps_hat, eps)
    p_sample_loop / ddim_sample_loop -> images
    """

    def __init__(self, num_timesteps=1000, beta_start=1e-4, beta_end=2e-2):
        self.T = num_timesteps
        betas = jnp.linspace(beta_start, beta_end, num_timesteps,
                             dtype=jnp.float32)
        alphas = 1.0 - betas
        acp = jnp.cumprod(alphas)
        self.betas = betas
        self.alphas = alphas
        self.alphas_cumprod = acp
        self.sqrt_acp = jnp.sqrt(acp)
        self.sqrt_1m_acp = jnp.sqrt(1.0 - acp)

    def q_sample(self, x0, t, noise):
        """Forward noising: x_t = sqrt(acp_t) x0 + sqrt(1-acp_t) eps."""
        a = self.sqrt_acp[t][:, None, None, None]
        b = self.sqrt_1m_acp[t][:, None, None, None]
        return a * x0 + b * noise

    def train_loss(self, model, x0, y=None):
        from ..framework.random import rng_key
        def _f(x0a, *ya):
            k1, k2 = jax.random.split(rng_key())
            t = jax.random.randint(k1, (x0a.shape[0],), 0, self.T)
            noise = jax.random.normal(k2, x0a.shape, x0a.dtype)
            return t, noise
        t, noise = apply_op("ddpm_draw", _f, x0)
        xt = apply_op("q_sample", lambda a, tt, nn_: self.q_sample(a, tt, nn_),
                      x0, t, noise)
        eps = model(xt, t, y)
        if model.cfg.learn_sigma:
            eps = apply_op("split_eps",
                           lambda a: a[:, :a.shape[1] // 2], eps)
        return F.mse_loss(eps, noise)

    # -- sampling ----------------------------------------------------------
    def _model_eps(self, model, x, t, y):
        eps = model(x, t, y)
        if model.cfg.learn_sigma:
            eps = apply_op("split_eps", lambda a: a[:, :a.shape[1] // 2], eps)
        return eps

    def p_sample_loop(self, model, shape, y=None, seed=0):
        """Ancestral DDPM sampling (eager loop over T steps)."""
        from ..core.autograd import no_grad
        key = jax.random.PRNGKey(seed)
        key, k0 = jax.random.split(key)
        x = Tensor(jax.random.normal(k0, shape, jnp.float32))
        with no_grad():
            for i in range(self.T - 1, -1, -1):
                t = Tensor(jnp.full((shape[0],), i, jnp.int32))
                eps = self._model_eps(model, x, t, y)
                beta = self.betas[i]
                alpha = self.alphas[i]
                coef = beta / jnp.sqrt(1.0 - self.alphas_cumprod[i])
                key, kn = jax.random.split(key)
                def _step(xa, ea):
                    mean = (xa - coef * ea) / jnp.sqrt(alpha)
                    if i == 0:
                        return mean
                    z = jax.random.normal(kn, xa.shape, xa.dtype)
                    return mean + jnp.sqrt(beta) * z
                x = apply_op("p_sample", _step, x, eps)
        return x

    def ddim_sample_loop(self, model, shape, y=None, steps=50, eta=0.0,
                         seed=0):
        """DDIM (deterministic when eta=0) with `steps` spaced timesteps."""
        from ..core.autograd import no_grad
        key = jax.random.PRNGKey(seed)
        key, k0 = jax.random.split(key)
        x = Tensor(jax.random.normal(k0, shape, jnp.float32))
        ts = jnp.linspace(self.T - 1, 0, steps).astype(jnp.int32)
        with no_grad():
            for n in range(steps):
                i = int(ts[n])
                j = int(ts[n + 1]) if n + 1 < steps else -1
                t = Tensor(jnp.full((shape[0],), i, jnp.int32))
                eps = self._model_eps(model, x, t, y)
                a_t = self.alphas_cumprod[i]
                a_prev = self.alphas_cumprod[j] if j >= 0 \
                    else jnp.asarray(1.0, jnp.float32)
                key, kn = jax.random.split(key)
                def _step(xa, ea):
                    x0 = (xa - jnp.sqrt(1 - a_t) * ea) / jnp.sqrt(a_t)
                    sigma = eta * jnp.sqrt((1 - a_prev) / (1 - a_t)
                                           * (1 - a_t / a_prev))
                    dir_xt = jnp.sqrt(jnp.maximum(1 - a_prev - sigma ** 2,
                                                  0.0)) * ea
                    out = jnp.sqrt(a_prev) * x0 + dir_xt
                    if eta > 0:
                        out = out + sigma * jax.random.normal(
                            kn, xa.shape, xa.dtype)
                    return out
                x = apply_op("ddim_step", _step, x, eps)
        return x
