"""Top-level namespace tail: module-level in-place ops, Place classes,
and small utilities.

Parity: reference `python/paddle/__init__.py` exports — the `op_`
in-place variants are already Tensor methods (ops/methods.py); this
module lifts them to module functions the way the reference does.
Place classes collapse onto jax devices (`paddle/phi/common/place.h`):
on a TPU build CUDAPlace is absent hardware, so it maps to the default
accelerator slot for API compatibility.
"""
from __future__ import annotations

import numpy as np

from .core.tensor import Tensor

__all__ = ["CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "XPUPlace",
           "CustomPlace", "shape", "tolist", "reverse", "batch",
           "set_printoptions", "disable_signal_handler", "check_shape",
           "set_cuda_rng_state", "get_cuda_rng_state"]


class _Place:
    _kind = "undefined"

    def __init__(self, device_id=0):
        self._id = int(device_id)

    def __repr__(self):
        return f"Place({self._kind}:{self._id})" if self._kind != "cpu" \
            else "Place(cpu)"

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._id == getattr(other, "_id", None))


class CPUPlace(_Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)


class CUDAPlace(_Place):
    """Accelerator slot i — on this build the attached TPU/XLA device
    (kept for API compatibility with reference code that constructs
    CUDAPlace)."""
    _kind = "accelerator"


class CUDAPinnedPlace(_Place):
    _kind = "pinned"

    def __init__(self):
        super().__init__(0)


class XPUPlace(_Place):
    _kind = "xpu"


class CustomPlace(_Place):
    _kind = "custom"

    def __init__(self, dev_type, device_id=0):
        super().__init__(device_id)
        self._kind = str(dev_type)


def shape(input, name=None):
    """Runtime shape as an int32 tensor (reference paddle.shape)."""
    import jax.numpy as jnp
    arr = input._data if isinstance(input, Tensor) else input
    return Tensor(jnp.asarray(arr.shape, jnp.int32))


def tolist(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x).tolist()


def reverse(x, axis, name=None):
    """Alias of flip (the reference keeps both names)."""
    from .ops.manipulation import flip
    return flip(x, axis)


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference paddle.batch)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Forward to numpy's print options (tensors repr through numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op: the reference installs C++ signal handlers that this build
    never registers."""


def check_shape(x):
    """Static-graph shape check hook — shapes are always concrete here."""
    return shape(x)


def set_cuda_rng_state(state):
    """Maps onto the single framework RNG stream (no separate CUDA
    generator on a TPU build)."""
    from .framework.random import set_rng_state
    set_rng_state(state)


def get_cuda_rng_state():
    from .framework.random import get_rng_state
    return get_rng_state()


def _export_inplace(ns):
    """Lift every Tensor `op_` in-place method to a module function
    (reference exports them at top level)."""
    made = []
    for name in dir(Tensor):
        if not name.endswith("_") or name.startswith("_"):
            continue
        if name in ns:
            continue
        meth = getattr(Tensor, name)
        if not callable(meth):
            continue

        def fn(x, *args, _m=name, **kw):
            return getattr(x, _m)(*args, **kw)
        fn.__name__ = name
        fn.__doc__ = f"In-place variant (Tensor.{name}); returns x."
        ns[name] = fn
        made.append(name)
    return made
