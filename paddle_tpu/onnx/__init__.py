"""paddle.onnx — export facade.

Parity: reference `python/paddle/onnx/export.py` (delegates to
paddle2onnx). Per SURVEY.md A.7 the TPU build's deployment artifact is
the StableHLO module written by jit.save: `onnx.export` keeps the
reference call shape and produces that artifact (ONNX protobuf emission
would need the paddle2onnx package, which is not shipped).
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export a Layer for deployment (reference onnx.export signature).

    Writes `{path}.pdiparams` + `{path}.pdmodel.mlir` (StableHLO) via
    jit.save — the portable compiled-program format of this build.
    input_spec is REQUIRED (the program artifact is traced from it), and
    every dimension must be concrete — XLA programs are static-shaped,
    so export one program per deployment batch size."""
    from ..jit import save as jit_save
    if input_spec is None:
        raise ValueError(
            "onnx.export needs input_spec: the compiled-program artifact "
            "is traced from it (e.g. input_spec=[InputSpec([8, 4], "
            "'float32')])")
    for spec in input_spec:
        shape = getattr(spec, "shape", None) or []
        if any(s is None or (isinstance(s, int) and s < 0) for s in shape):
            raise NotImplementedError(
                f"dynamic dim in {list(shape)}: StableHLO export is "
                "static-shaped — pass concrete sizes (one artifact per "
                "deployment shape)")
    jit_save(layer, path, input_spec=input_spec, **configs)
    return path
