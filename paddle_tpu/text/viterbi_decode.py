"""paddle.text.viterbi_decode — CRF Viterbi decoding.

Parity: reference `python/paddle/text/viterbi_decode.py` (the module the
package re-exports from; like the reference, `paddle.text.viterbi_decode`
the ATTRIBUTE resolves to the function after package import, while this
module path stays importable).

TPU-native: max-product forward + backtrace as two `lax.scan`s — static
shapes, no host loop; the backtrace gather vectorizes over the batch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_op

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding. Parity: text/viterbi_decode.py.

    potentials: (B, T, N) unary emissions; transition_params: (N, N);
    lengths: (B,) valid lengths. Returns (scores (B,), paths (B, T))."""

    def _f(emis, trans, lens):
        B, T, N = emis.shape
        lens = lens.astype(jnp.int32)
        if include_bos_eos_tag:
            # reference convention: tags N-2 = BOS, N-1 = EOS
            bos, eos = N - 2, N - 1
            alpha0 = emis[:, 0] + trans[bos][None, :]
        else:
            alpha0 = emis[:, 0]

        def step(carry, t):
            alpha = carry                               # (B, N)
            scores = alpha[:, :, None] + trans[None]    # (B, from, to)
            best = jnp.max(scores, axis=1) + emis[:, t]
            back = jnp.argmax(scores, axis=1)           # (B, N)
            # positions past the sequence end keep their alpha
            mask = (t < lens)[:, None]
            alpha = jnp.where(mask, best, alpha)
            back = jnp.where(mask, back,
                             jnp.arange(N, dtype=back.dtype)[None, :])
            return alpha, back

        if T == 1:
            alpha = alpha0
            if include_bos_eos_tag:
                alpha = alpha + trans[:, eos][None, :]
            scores = jnp.max(alpha, axis=1)
            last = jnp.argmax(alpha, axis=1)
            return scores, last[:, None].astype(jnp.int64)
        alpha, backs = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        scores = jnp.max(alpha, axis=1)
        last = jnp.argmax(alpha, axis=1)                # (B,)

        def trace(carry, back_t):
            tag = carry                                 # (B,)
            prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
            return prev, tag

        _, path_rev = jax.lax.scan(trace, last, backs, reverse=True)
        # path_rev: (T-1, B) tags for steps 1..T-1 — prepend step-0 tags
        first = jnp.where(
            (1 < lens), jnp.take_along_axis(
                backs[0], path_rev[0][:, None], axis=1)[:, 0], last)
        paths = jnp.concatenate([first[None], path_rev], axis=0).T  # (B, T)
        return scores, paths.astype(jnp.int64)

    return apply_op("viterbi_decode", _f, potentials, transition_params,
                    lengths)


class ViterbiDecoder(Layer):
    """Parity: text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
