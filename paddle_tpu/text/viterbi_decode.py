"""paddle.text.viterbi_decode — module-path parity (reference
text/viterbi_decode.py); implementations live in paddle_tpu.text."""
from . import viterbi_decode, ViterbiDecoder  # noqa: F401

__all__ = ["viterbi_decode", "ViterbiDecoder"]
