"""paddle.text — sequence decoding + dataset namespace.

Parity: reference `python/paddle/text/` — ViterbiDecoder/viterbi_decode
(`text/viterbi_decode.py`) plus the dataset zoo (Conll05st, Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16 in `text/datasets/`).

TPU-native: Viterbi runs as a lax.scan over time steps (max-product
forward + backtrace gather) — static shapes, no host loop. The dataset
classes load from a user-supplied local path; this environment has no
network egress, so the auto-download path raises with instructions
instead of silently hanging.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .viterbi_decode import viterbi_decode, ViterbiDecoder  # noqa: F401

__all__ = ["viterbi_decode", "ViterbiDecoder", "Conll05st", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


class _LocalTextDataset:
    """Dataset shells: parse a user-supplied local copy (this build has no
    network egress, so the reference's auto-download path is refused with
    instructions rather than attempted)."""

    URL = None

    def __init__(self, data_file=None, mode="train", **kwargs):
        self.mode = mode
        if data_file is None:
            raise RuntimeError(
                f"{type(self).__name__}: automatic download is unavailable "
                f"in this environment; pass data_file= pointing at a local "
                f"copy ({self.URL})")
        self.data_file = data_file

    def __len__(self):
        raise RuntimeError("dataset not loaded")


class Conll05st(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"


class Imdb(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"


class Imikolov(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"


class Movielens(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"


class UCIHousing(_LocalTextDataset):
    URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/"


class WMT14(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/wmt_shrinked_data/wmt14.tgz"


class WMT16(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/wmt16%2Fwmt16.tar.gz"

