"""paddle.text — sequence decoding + dataset namespace.

Parity: reference `python/paddle/text/` — ViterbiDecoder/viterbi_decode
(`text/viterbi_decode.py`) plus the dataset zoo (Conll05st, Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16 in `text/datasets/`).

TPU-native: Viterbi runs as a lax.scan over time steps (max-product
forward + backtrace gather) — static shapes, no host loop. The dataset
classes load from a user-supplied local path; this environment has no
network egress, so the auto-download path raises with instructions
instead of silently hanging.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_op

__all__ = ["viterbi_decode", "ViterbiDecoder", "Conll05st", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding. Parity: text/viterbi_decode.py.

    potentials: (B, T, N) unary emissions; transition_params: (N, N);
    lengths: (B,) valid lengths. Returns (scores (B,), paths (B, T))."""

    def _f(emis, trans, lens):
        B, T, N = emis.shape
        lens = lens.astype(jnp.int32)
        if include_bos_eos_tag:
            # reference convention: tags N-2 = BOS, N-1 = EOS
            bos, eos = N - 2, N - 1
            alpha0 = emis[:, 0] + trans[bos][None, :]
        else:
            alpha0 = emis[:, 0]

        def step(carry, t):
            alpha = carry                               # (B, N)
            scores = alpha[:, :, None] + trans[None]    # (B, from, to)
            best = jnp.max(scores, axis=1) + emis[:, t]
            back = jnp.argmax(scores, axis=1)           # (B, N)
            # positions past the sequence end keep their alpha
            mask = (t < lens)[:, None]
            alpha = jnp.where(mask, best, alpha)
            back = jnp.where(mask, back,
                             jnp.arange(N, dtype=back.dtype)[None, :])
            return alpha, back

        if T == 1:
            alpha = alpha0
            if include_bos_eos_tag:
                alpha = alpha + trans[:, eos][None, :]
            scores = jnp.max(alpha, axis=1)
            last = jnp.argmax(alpha, axis=1)
            return scores, last[:, None].astype(jnp.int64)
        alpha, backs = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        scores = jnp.max(alpha, axis=1)
        last = jnp.argmax(alpha, axis=1)                # (B,)

        def trace(carry, back_t):
            tag = carry                                 # (B,)
            prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
            return prev, tag

        _, path_rev = jax.lax.scan(trace, last, backs, reverse=True)
        # path_rev: (T-1, B) tags for steps 1..T-1 — prepend step-0 tags
        first = jnp.where(
            (1 < lens), jnp.take_along_axis(
                backs[0], path_rev[0][:, None], axis=1)[:, 0], last)
        paths = jnp.concatenate([first[None], path_rev], axis=0).T  # (B, T)
        return scores, paths.astype(jnp.int64)

    return apply_op("viterbi_decode", _f, potentials, transition_params,
                    lengths)


class ViterbiDecoder(Layer):
    """Parity: text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _LocalTextDataset:
    """Dataset shells: parse a user-supplied local copy (this build has no
    network egress, so the reference's auto-download path is refused with
    instructions rather than attempted)."""

    URL = None

    def __init__(self, data_file=None, mode="train", **kwargs):
        self.mode = mode
        if data_file is None:
            raise RuntimeError(
                f"{type(self).__name__}: automatic download is unavailable "
                f"in this environment; pass data_file= pointing at a local "
                f"copy ({self.URL})")
        self.data_file = data_file

    def __len__(self):
        raise RuntimeError("dataset not loaded")


class Conll05st(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"


class Imdb(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"


class Imikolov(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"


class Movielens(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"


class UCIHousing(_LocalTextDataset):
    URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/"


class WMT14(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/wmt_shrinked_data/wmt14.tgz"


class WMT16(_LocalTextDataset):
    URL = "https://dataset.bj.bcebos.com/wmt16%2Fwmt16.tar.gz"

