"""paddle.inference — deployment predictor.

Parity: reference inference API (`paddle/fluid/inference/api/
paddle_inference_api.h:81` Predictor, python `paddle.inference.Config` /
`create_predictor`, zero-copy handles) over the AnalysisPredictor engine.

TPU-native collapse (SURVEY.md A.7): the offline-optimization pipeline
(IR fusion passes, memory optimize, TRT subgraphs) IS XLA — jit.save
exports a StableHLO module, and the Predictor deserializes and runs it
through the same compiler the reference funnels through its analysis
passes. The named-handle copy_from_cpu/run/copy_to_cpu protocol is kept
verbatim so serving code ports unchanged.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor",
           "get_version"]


def get_version():
    from .. import __version__
    return __version__


class Config:
    """Parity: paddle.inference.Config. Accepts the reference's tuning
    toggles (recorded; XLA owns optimization on TPU)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and params_file is None and \
                os.path.isdir(prog_file):
            # Config(model_dir) form
            base = os.path.join(prog_file, "model")
            prog_file, params_file = base + ".pdmodel.mlir", \
                base + ".pdiparams"
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_gpu = False
        self._mem_optim = True
        self._ir_optim = True
        self._cpu_threads = 1
        # every toggle call is recorded here, no-op or not, so deployed
        # configs stay introspectable (summary()) even though XLA owns
        # the actual optimization decisions on TPU
        self._settings: Dict[str, object] = {}

    # ---- reference toggle surface (recorded, XLA decides) ----
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True
        self._settings["use_gpu"] = True
        self._settings["gpu_memory_pool_mb"] = memory_pool_init_size_mb
        self._settings["gpu_device_id"] = device_id

    def disable_gpu(self):
        self._use_gpu = False
        self._settings["use_gpu"] = False

    def enable_memory_optim(self, x=True):
        self._mem_optim = x
        self._settings["memory_optim"] = x

    def switch_ir_optim(self, x=True):
        self._ir_optim = x
        self._settings["ir_optim"] = x

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = n
        self._settings["cpu_math_library_num_threads"] = n

    def enable_mkldnn(self):
        self._settings["mkldnn"] = True

    def disable_glog_info(self):
        self._settings["glog_info"] = False

    def model_dir(self):
        return os.path.dirname(self.prog_file or "")

    def summary(self):
        """The recorded configuration: file paths + every toggle the
        caller set (reference Config::Summary(), analysis_config.cc).
        Returns the formatted table; `.settings()` gives the raw dict."""
        rows = [("prog_file", self.prog_file),
                ("params_file", self.params_file),
                ("use_gpu", self._use_gpu),
                ("memory_optim", self._mem_optim),
                ("ir_optim", self._ir_optim),
                ("cpu_math_threads", self._cpu_threads)]
        rows += sorted((k, v) for k, v in self._settings.items()
                       if k not in dict(rows))
        width = max(len(k) for k, _ in rows)
        return "\n".join(f"{k:<{width}}  {v}" for k, v in rows)

    def settings(self):
        return dict(self._settings)


class PredictorTensor:
    """Named IO handle (parity: paddle_infer::Tensor zero-copy handle)."""

    def __init__(self, name, spec=None):
        self.name = name
        self._spec = spec or {}
        self._value = None

    def copy_from_cpu(self, data):
        self._value = jnp.asarray(np.asarray(data))

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def shape(self):
        if self._value is not None:
            return list(self._value.shape)
        return list(self._spec.get("shape", []))

    def type(self):
        return self._spec.get("dtype", "float32")


class Predictor:
    """Parity: paddle_infer::Predictor (get_input_names/get_input_handle/
    run/get_output_handle protocol)."""

    def __init__(self, config: Config):
        import jax.export
        import pickle

        self._config = config
        base = config.prog_file
        if base is None:
            raise ValueError("Config needs a model file")
        if base.endswith(".pdmodel.mlir"):
            base = base[:-len(".pdmodel.mlir")]
        with open(base + ".pdmodel.mlir", "rb") as f:
            self._exported = jax.export.deserialize(f.read())
        params_file = config.params_file or base + ".pdiparams"
        with open(params_file, "rb") as f:
            state = pickle.load(f)
        self._state = {k: jnp.asarray(v) for k, v in state.items()}
        meta_path = base + ".pdmodel.meta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            self._input_meta = meta.get("inputs", [])
        else:
            n_in = len(self._exported.in_avals[1]) \
                if len(self._exported.in_avals) > 1 else 1
            self._input_meta = [{"name": f"x{i}"} for i in range(n_in)]
        self._inputs: Dict[str, PredictorTensor] = {
            m["name"]: PredictorTensor(m["name"], m)
            for m in self._input_meta}
        self._outputs: List[PredictorTensor] = []

    # ---- reference handle protocol ----
    def get_input_names(self):
        return [m["name"] for m in self._input_meta]

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Zero-arg form runs from the named handles (reference protocol);
        passing a list of numpy arrays returns outputs directly (the
        reference's convenience overload)."""
        if inputs is not None:
            for m, a in zip(self._input_meta, inputs):
                self._inputs[m["name"]].copy_from_cpu(a)
        args = [self._inputs[m["name"]]._value for m in self._input_meta]
        if any(a is None for a in args):
            missing = [m["name"] for m, a in zip(self._input_meta, args)
                       if a is None]
            raise RuntimeError(f"inputs not set: {missing}")
        out = self._exported.call(self._state, *args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for i, o in enumerate(outs):
            h = PredictorTensor(f"out{i}")
            h._value = o
            self._outputs.append(h)
        if inputs is not None:
            return [np.asarray(o._value) for o in self._outputs]
        return True

    def get_output_names(self):
        return [h.name for h in self._outputs] or ["out0"]

    def get_output_handle(self, name):
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def try_shrink_memory(self):
        pass

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config: Config) -> Predictor:
    """Parity: paddle.inference.create_predictor."""
    return Predictor(config)


class DataType:
    """Parity: inference.DataType (paddle_infer_declare.h enum)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7
    FLOAT64 = 8


class PlaceType:
    """Parity: inference.PlaceType. kCUSTOM covers the TPU device."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class PrecisionType:
    """Parity: inference.PrecisionType (AnalysisConfig::Precision)."""
    Float32 = 0
    Int8 = 1
    Half = 2
    Bfloat16 = 3


Tensor = PredictorTensor  # reference exports the handle type as Tensor


class PredictorPool:
    """Parity: inference.PredictorPool — N predictors over one model."""

    def __init__(self, config, size=1):
        self._preds = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._preds[idx]


def get_num_bytes_of_data_type(dtype):
    return {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
            DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
            DataType.BFLOAT16: 2, DataType.BOOL: 1,
            DataType.FLOAT64: 8}.get(dtype, 4)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Parity: inference.convert_to_mixed_precision — rewrite a saved
    artifact's parameters to bf16 (the serving-side precision on TPU).
    The StableHLO program stays as exported; parameters are cast at load
    by the Predictor, so only the params artifact is rewritten."""
    import pickle
    import numpy as np
    import ml_dtypes
    with open(params_file, "rb") as f:
        state = pickle.load(f)
    out = {k: (v.astype(ml_dtypes.bfloat16)
               if isinstance(v, np.ndarray) and v.dtype == np.float32
               else v)
           for k, v in state.items()}
    with open(mixed_params_file, "wb") as f:
        pickle.dump(out, f)
    if model_file != mixed_model_file:
        import shutil
        for ext in ("", ".meta.json"):
            try:
                shutil.copy(model_file + ext, mixed_model_file + ext)
            except FileNotFoundError:
                pass
    return mixed_params_file


def get_trt_compile_version():
    return (0, 0, 0)   # no TensorRT on TPU (API parity only)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """Parity: inference._get_phi_kernel_name — maps a legacy op name to
    its phi kernel; here the registry key IS the kernel name."""
    return op_name


class XpuConfig:
    """Parity: inference.XpuConfig — config holder; XPU backends are not
    part of the TPU build (constructing is allowed, attaching raises)."""

    def __init__(self, **kwargs):
        self.__dict__.update(kwargs)


__all__ += ["DataType", "PlaceType", "PrecisionType", "Tensor",
            "PredictorPool", "get_num_bytes_of_data_type",
            "convert_to_mixed_precision", "get_trt_compile_version",
            "get_trt_runtime_version", "_get_phi_kernel_name", "XpuConfig"]
