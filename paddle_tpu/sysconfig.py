"""paddle.sysconfig — install-tree introspection.

Parity: reference `python/paddle/sysconfig.py` (get_include/get_lib).
Here the headers/libs of interest are the native extension's
(_native/), plus jaxlib's for XLA-adjacent builds.
"""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of the C headers shipped with the native runtime."""
    return os.path.join(_ROOT, "_native", "include")


def get_lib() -> str:
    """Directory holding the built native shared objects."""
    return os.path.join(_ROOT, "_native", "lib")
