"""paddle_tpu.static — static-graph mode over the eager tape.

Parity: reference `python/paddle/static/` — `paddle.static.data`
placeholders, `Program`/`program_guard`, `Executor.run(feed, fetch_list)`
(`base/executor.py:1234` -> StandaloneExecutor). The heavyweight machinery
(ProgramDesc, PIR lowering, interpreter) is replaced by XLA per SURVEY.md
§7; what this module KEEPS working is the scripting pattern:

    x = paddle.static.data("x", [None, 8])
    y = net(x)                       # ops record on the tape as usual
    exe = paddle.static.Executor()
    out, = exe.run(feed={"x": batch}, fetch_list=[y])

TPU-native: every taped GradNode carries its array-level forward closure,
so the recorded graph IS a re-runnable program — `Executor.run` walks the
producer DAG of the fetches in forward-topological order, substituting
feed values at the `data` placeholders. The replay is jitted and cached
per (fetch set, feed shapes), playing the StandaloneExecutor role.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..jit.api import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "enable_static",
           "disable_static", "in_static_mode"]

_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_static_mode():
    return _static_mode[0]


class Program:
    """Records the data placeholders created under it; the op graph itself
    lives on the tape (GradNode DAG)."""

    def __init__(self):
        self._is_start_up = False
        self.placeholders: List[Tensor] = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()
_current = [_main]


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self._prog = main_program or Program()

    def __enter__(self):
        _current.append(self._prog)
        return self._prog

    def __exit__(self, *a):
        _current.pop()
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder variable (parity: paddle.static.data). Returns a Tensor
    of zeros with dynamic (None/-1) dims materialized as 1 — the value is
    a tracing stand-in; Executor.run substitutes the feed."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    shp = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    t = Tensor(jnp.zeros(shp, jnp.dtype(convert_dtype(dtype) or "float32")),
               stop_gradient=False, name=name)
    t._spec = None
    _current[-1].placeholders.append(t)
    return t


def _forward_topo(fetch_tensors):
    """Forward-topological order of GradNodes producing the fetches."""
    order, visited = [], set()
    stack = []
    for t in fetch_tensors:
        n = t._grad_node
        if n is not None:
            stack.append((n, False))
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            parent = t._grad_node
            if parent is not None and id(parent) not in visited:
                stack.append((parent, False))
    return order  # leaves-first


class Executor:
    """Parity: paddle.static.Executor — replays the fetches' producer DAG
    with feeds substituted, compiled per (fetches, feed shapes)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        # run statistics (parity: new_executor/executor_statistics.cc —
        # per-op instruction counts + run timings, dumpable as JSON)
        self._stats = {"runs": 0, "compiles": 0, "op_counts": {},
                       "total_run_time_s": 0.0, "last_run_time_s": 0.0}

    def statistics(self):
        """Executor run statistics: runs, compiles, per-op replay counts,
        wall times (the reference's executor-statistics dump)."""
        return dict(self._stats, op_counts=dict(self._stats["op_counts"]))

    def run(self, program=None, feed: Optional[Dict] = None,
            fetch_list: Optional[List] = None, return_numpy=True):
        feed = feed or {}
        fetch_list = fetch_list or []
        prog = program if isinstance(program, Program) else _current[-1]
        # resolve feed names onto placeholder tensors
        by_name = {p.name: p for p in prog.placeholders}
        feed_ts, feed_vals = [], []
        for k, v in feed.items():
            t = k if isinstance(k, Tensor) else by_name.get(k)
            if t is None:
                raise KeyError(f"feed {k!r} is not a static.data placeholder "
                               f"of this program")
            feed_ts.append(t)
            feed_vals.append(np.asarray(v))

        import time as _time
        _t0 = _time.perf_counter()
        nodes = _forward_topo(fetch_list)
        for n in nodes:
            if n.fwd_closed is None:
                raise RuntimeError(
                    f"node {n.name} was released (backward already ran "
                    "without retain_graph); rebuild the program")

        key = (tuple(id(t) for t in fetch_list),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple(id(t) for t in feed_ts))
        fn = self._cache.get(key)
        if fn is None:
            feed_ids = [id(t) for t in feed_ts]

            def replay(vals):
                produced = {}

                def value(t):
                    if id(t) in feed_ids:
                        return vals[feed_ids.index(id(t))]
                    node = t._grad_node
                    if node is not None and (id(node), t._grad_out_idx) \
                            in produced:
                        return produced[(id(node), t._grad_out_idx)]
                    return t._data

                for node in nodes:
                    outs = node.fwd_closed(*[value(t) for t in node.inputs])
                    leaves = jax.tree_util.tree_leaves(outs)
                    for i, o in enumerate(leaves):
                        produced[(id(node), i)] = o
                return [value(t) for t in fetch_list]

            fn = jax.jit(replay)
            self._cache[key] = fn
            self._stats["compiles"] += 1
        outs = fn(feed_vals)
        self._stats["runs"] += 1
        for n in nodes:
            oc = self._stats["op_counts"]
            oc[n.name] = oc.get(n.name, 0) + 1
        dt = _time.perf_counter() - _t0
        self._stats["last_run_time_s"] = dt
        self._stats["total_run_time_s"] += dt
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def executor_statistics(executor, path=None):
    """Dump an Executor's run statistics, optionally to a JSON file
    (parity: `new_executor/executor_statistics.cc` dump)."""
    import json
    stats = executor.statistics()
    if path is not None:
        with open(path, "w") as f:
            json.dump(stats, f, indent=2)
    return stats
