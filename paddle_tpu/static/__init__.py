"""paddle_tpu.static — compatibility shims.

The reference's static-graph mode (Program/Executor,
`python/paddle/static/`) is replaced wholesale by jax.jit tracing
(paddle_tpu.jit.to_static); see SURVEY.md §7 design stance. This module
keeps the commonly-scripted entry points as thin adapters so reference
scripts import cleanly.
"""
from ..jit.api import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program"]


class Program:
    """Inert placeholder; compiled programs are XLA executables."""

    def __init__(self):
        self._is_start_up = False

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
