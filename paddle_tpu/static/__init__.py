"""paddle_tpu.static — static-graph mode over the eager tape.

Parity: reference `python/paddle/static/` — `paddle.static.data`
placeholders, `Program`/`program_guard`, `Executor.run(feed, fetch_list)`
(`base/executor.py:1234` -> StandaloneExecutor). The heavyweight machinery
(ProgramDesc, PIR lowering, interpreter) is replaced by XLA per SURVEY.md
§7; what this module KEEPS working is the scripting pattern:

    x = paddle.static.data("x", [None, 8])
    y = net(x)                       # ops record on the tape as usual
    exe = paddle.static.Executor()
    out, = exe.run(feed={"x": batch}, fetch_list=[y])

TPU-native: every taped GradNode carries its array-level forward closure,
so the recorded graph IS a re-runnable program — `Executor.run` walks the
producer DAG of the fetches in forward-topological order, substituting
feed values at the `data` placeholders. The replay is jitted and cached
per (fetch set, feed shapes), playing the StandaloneExecutor role.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..jit.api import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "enable_static",
           "disable_static", "in_static_mode"]

_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_static_mode():
    return _static_mode[0]


class Program:
    """Records the data placeholders created under it; the op graph itself
    lives on the tape (GradNode DAG)."""

    def __init__(self):
        self._is_start_up = False
        self.placeholders: List[Tensor] = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()
_current = [_main]


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self._prog = main_program or Program()

    def __enter__(self):
        _current.append(self._prog)
        return self._prog

    def __exit__(self, *a):
        _current.pop()
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder variable (parity: paddle.static.data). Returns a Tensor
    of zeros with dynamic (None/-1) dims materialized as 1 — the value is
    a tracing stand-in; Executor.run substitutes the feed."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    shp = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    t = Tensor(jnp.zeros(shp, jnp.dtype(convert_dtype(dtype) or "float32")),
               stop_gradient=False, name=name)
    t._spec = None
    _current[-1].placeholders.append(t)
    return t


def _forward_topo(fetch_tensors):
    """Forward-topological order of GradNodes producing the fetches."""
    order, visited = [], set()
    stack = []
    for t in fetch_tensors:
        n = t._grad_node
        if n is not None:
            stack.append((n, False))
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            parent = t._grad_node
            if parent is not None and id(parent) not in visited:
                stack.append((parent, False))
    return order  # leaves-first


class Executor:
    """Parity: paddle.static.Executor — replays the fetches' producer DAG
    with feeds substituted, compiled per (fetches, feed shapes)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        # run statistics (parity: new_executor/executor_statistics.cc —
        # per-op instruction counts + run timings, dumpable as JSON)
        self._stats = {"runs": 0, "compiles": 0, "op_counts": {},
                       "total_run_time_s": 0.0, "last_run_time_s": 0.0}

    def statistics(self):
        """Executor run statistics: runs, compiles, per-op replay counts,
        wall times (the reference's executor-statistics dump)."""
        return dict(self._stats, op_counts=dict(self._stats["op_counts"]))

    def run(self, program=None, feed: Optional[Dict] = None,
            fetch_list: Optional[List] = None, return_numpy=True):
        feed = feed or {}
        fetch_list = fetch_list or []
        prog = program if isinstance(program, Program) else _current[-1]
        # resolve feed names onto placeholder tensors
        by_name = {p.name: p for p in prog.placeholders}
        feed_ts, feed_vals = [], []
        for k, v in feed.items():
            t = k if isinstance(k, Tensor) else by_name.get(k)
            if t is None:
                raise KeyError(f"feed {k!r} is not a static.data placeholder "
                               f"of this program")
            feed_ts.append(t)
            feed_vals.append(np.asarray(v))

        import time as _time
        _t0 = _time.perf_counter()
        nodes = _forward_topo(fetch_list)
        for n in nodes:
            if n.fwd_closed is None:
                raise RuntimeError(
                    f"node {n.name} was released (backward already ran "
                    "without retain_graph); rebuild the program")

        # non-feed leaf tensors (parameters/state) enter as RUNTIME args,
        # not trace-time constants — mutating w._data between runs must be
        # visible on the next run (reference Executor reads the scope)
        feed_id_set = {id(t) for t in feed_ts}
        leaf_ts, leaf_seen = [], set()
        for node in nodes:
            for t in node.inputs:
                if (t._grad_node is None and id(t) not in feed_id_set
                        and id(t) not in leaf_seen):
                    leaf_seen.add(id(t))
                    leaf_ts.append(t)
        for t in fetch_list:
            if (t._grad_node is None and id(t) not in feed_id_set
                    and id(t) not in leaf_seen):
                leaf_seen.add(id(t))
                leaf_ts.append(t)

        key = (tuple(id(t) for t in fetch_list),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple(id(t) for t in feed_ts),
               tuple(id(t) for t in leaf_ts))
        fn = self._cache.get(key)
        if fn is None:
            feed_ids = [id(t) for t in feed_ts]
            leaf_ids = [id(t) for t in leaf_ts]

            def replay(vals, leaf_vals):
                produced = {}

                def value(t):
                    if id(t) in feed_ids:
                        return vals[feed_ids.index(id(t))]
                    if id(t) in leaf_ids:
                        return leaf_vals[leaf_ids.index(id(t))]
                    node = t._grad_node
                    if node is not None and (id(node), t._grad_out_idx) \
                            in produced:
                        return produced[(id(node), t._grad_out_idx)]
                    return t._data

                for node in nodes:
                    outs = node.fwd_closed(*[value(t) for t in node.inputs])
                    leaves = jax.tree_util.tree_leaves(outs)
                    for i, o in enumerate(leaves):
                        produced[(id(node), i)] = o
                return [value(t) for t in fetch_list]

            fn = jax.jit(replay)
            self._cache[key] = fn
            self._stats["compiles"] += 1
        outs = fn(feed_vals, [t._data for t in leaf_ts])
        self._stats["runs"] += 1
        for n in nodes:
            oc = self._stats["op_counts"]
            oc[n.name] = oc.get(n.name, 0) + 1
        dt = _time.perf_counter() - _t0
        self._stats["last_run_time_s"] = dt
        self._stats["total_run_time_s"] += dt
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def executor_statistics(executor, path=None):
    """Dump an Executor's run statistics, optionally to a JSON file
    (parity: `new_executor/executor_statistics.cc` dump)."""
    import json
    stats = executor.statistics()
    if path is not None:
        with open(path, "w") as f:
            json.dump(stats, f, indent=2)
    return stats


# ------------------------------------------------------- static API tail
# Parity: reference `python/paddle/static/__init__.py` surface. The
# static-graph substrate here is the taped producer DAG replayed by
# Executor (above); Program/Scope-era helpers map onto it or onto the
# eager state that replaced them.

def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Build grads for a static loss (parity: base/backward.py
    append_backward): runs the tape backward and returns
    (param, grad) pairs."""
    from ..core import autograd as _ag
    params = parameter_list
    if params is None:
        params = [t for t in _collect_leaves(loss) if t is not None]
    # create_graph: the backward ops must land on the tape so
    # Executor.run can replay them against feeds
    grads = _ag.grad([loss], params, retain_graph=True, allow_unused=True,
                     create_graph=True)
    return [(p, g) for p, g in zip(params, grads)]


def _collect_leaves(t):
    seen, out, stack = set(), [], [t]
    while stack:
        cur = stack.pop()
        node = cur._grad_node
        if node is None:
            if not cur.stop_gradient and id(cur) not in seen:
                seen.add(id(cur))
                out.append(cur)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.extend(node.inputs)
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None,
              name=None):
    """Parity: paddle.static.gradients."""
    from ..core import autograd as _ag
    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gs = _ag.grad(list(ts), list(ins), grad_outputs=target_gradients,
                  retain_graph=True, allow_unused=True, create_graph=True)
    return list(gs)


class _Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()


Scope = _Scope


class BuildStrategy:
    """Graph-build knobs (parity: BuildStrategy). XLA owns fusion and
    memory planning; fields are accepted and recorded."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = 0


class CompiledProgram:
    """Parity: static.CompiledProgram — in this build every Executor.run
    is XLA-compiled already; the wrapper carries the strategy."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()


def name_scope(prefix=None):
    """Naming-only scope (parity: static.name_scope; names are cosmetic
    here — XLA owns the program structure)."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        yield
    return _cm()


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _cm():
        yield
    return _cm()


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Parity: static.Print — eager print of the tensor value."""
    import numpy as np
    arr = np.asarray(input._data)
    flat = arr.reshape(-1)
    shown = flat if summarize < 0 else flat[:summarize]
    print(f"{message or ''} {'var' if print_tensor_name else ''} "
          f"shape={list(arr.shape)}\n{shown}")
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Parity: static.py_func — in eager-first execution the python fn
    simply runs (jax.pure_callback would be the traced analog)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..ops.creation import full
    t = full(shape, value, dtype=dtype)
    t.stop_gradient = True
    global_scope().vars[name or f"gvar_{id(t)}"] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.initializer import _init_tensor
    from ..core.dtype import convert_dtype
    return _init_tensor(tuple(int(s) for s in shape), convert_dtype(dtype),
                        default_initializer, is_bias=is_bias)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from ..metric import Auc
    m = Auc(num_thresholds=min(num_thresholds, 4095))
    m.update(input, label)
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    return Tensor(jnp.asarray(m.accumulate(), jnp.float32))


class WeightNormParamAttr:
    """Parity: static.WeightNormParamAttr — carried config; apply weight
    norm with nn.utils.weight_norm in this build."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim, self.name, self.initializer = dim, name, initializer
        self.learning_rate, self.trainable = learning_rate, trainable


class ExponentialMovingAverage:
    """EMA of parameters (parity: static.ExponentialMovingAverage):
    update() after each step; apply()/restore() swap averaged weights."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._params = None
        self._ema = {}
        self._backup = None
        self._step = 0

    def update(self, parameters=None):
        if parameters is not None:
            self._params = list(parameters)
        if self._params is None:
            raise ValueError("pass parameters on the first update()")
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for i, p in enumerate(self._params):
            prev = self._ema.get(i, p._data)
            self._ema[i] = d * prev + (1 - d) * p._data

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def _cm():
            self._backup = [p._data for p in self._params]
            for i, p in enumerate(self._params):
                p._data = self._ema[i].astype(p._data.dtype)
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()
        return _cm()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._data = b
            self._backup = None


def cpu_places(device_count=None):
    from ..compat import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..compat import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..compat import XPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


Variable = None  # populated below to the Tensor class (static Variable
# collapsed into the eager Tensor in this build)


def _bind_variable():
    global Variable
    from ..core.tensor import Tensor as _T
    Variable = _T


_bind_variable()


# ------------------------------ save/load (program + persistables) -----
def save(program, model_path, protocol=4, **configs):
    """Persist a static Program's reachable parameters (parity:
    static.save)."""
    import pickle
    import numpy as np
    state = {f"p{i}": np.asarray(t._data)
             for i, t in enumerate(getattr(program, "parameters", []) or [])}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    params = getattr(program, "parameters", []) or []
    import jax.numpy as jnp
    for i, t in enumerate(params):
        key = f"p{i}"
        if key in state:
            t._data = jnp.asarray(state[key])


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Parity: static.save_inference_model — writes the feed/fetch
    contract; program bodies serialize through jit.save (StableHLO) when
    an input_spec-traced function is exported."""
    import pickle
    payload = {"feeds": [getattr(v, "name", f"feed_{i}")
                         for i, v in enumerate(feed_vars)],
               "fetches": len(fetch_vars)}
    import os
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel.meta", "wb") as f:
        pickle.dump(payload, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    import pickle
    with open(path_prefix + ".pdmodel.meta", "rb") as f:
        payload = pickle.load(f)
    return payload["feeds"], payload["fetches"]


def serialize_program(feed_vars, fetch_vars, **kwargs):
    import pickle
    return pickle.dumps({"feeds": len(feed_vars),
                         "fetches": len(fetch_vars)})


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    import pickle
    return pickle.dumps({})


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def deserialize_program(data):
    import pickle
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    import pickle
    return pickle.loads(data)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


def load_program_state(model_path, var_list=None):
    import pickle
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    import jax.numpy as jnp
    for i, t in enumerate(getattr(program, "parameters", []) or []):
        key = f"p{i}"
        if key in state_dict:
            t._data = jnp.asarray(state_dict[key])


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server CTR stack "
        "(out of the TPU north-star path; SURVEY.md A.7)")


def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU backends are not part of the TPU build")


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU backends are not part of the TPU build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU backends are not part of the TPU build")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU backends are not part of the TPU build")


# placed last: static.nn's module body only needs core/ops; its uses of
# global_scope/create_parameter are lazy (inside the layer builders)
from . import nn  # noqa: F401,E402
__all__.append("nn")
