"""paddle.static.nn — control flow + static-style layer builders.

Parity: reference `python/paddle/static/nn/__init__.py` (__all__ of 31
names: control_flow.py cond/case/switch_case/while_loop/static_pylayer,
common.py fc/embedding/conv*/norms/nce/row_conv/sequence_lod.py ops).

TPU-native semantics:

* Control flow is the real payload — these are the primitives dy2static
  rewrites python `if`/`while` into (reference
  dy2static/convert_operators.py). With a CONCRETE predicate they run
  the chosen branch eagerly (reference dygraph behavior). With a traced
  predicate (inside to_static) `cond`/`case`/`switch_case` execute every
  branch and select elementwise — gradients flow through the tape to
  both branches, and XLA dead-codes the unselected side where it can;
  `while_loop` lowers to `lax.while_loop` (forward-only under trace,
  like the reference's grad-restricted static While).
* Layer builders create their parameters inline (the static-graph
  convention); a `name=` reuses the parameter across rebuilds via the
  global scope, unnamed calls create fresh parameters.
* Sequence ops operate on padded (B, T, ...) tensors with an optional
  `seq_lens` in place of LoD.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
]


def _is_tracer(x):
    d = getattr(x, "_data", x)
    return isinstance(d, jax.core.Tracer)


def _as_bool(pred):
    d = getattr(pred, "_data", pred)
    return bool(np.asarray(d).reshape(()))


def _select_trees(pred, taken, other):
    """Elementwise select between two same-structure outputs; gradients
    flow into both (the untaken side's cotangent is zeroed by where)."""
    t_leaves, treedef = jax.tree_util.tree_flatten(
        taken, is_leaf=lambda x: isinstance(x, Tensor))
    o_leaves, treedef2 = jax.tree_util.tree_flatten(
        other, is_leaf=lambda x: isinstance(x, Tensor))
    if treedef != treedef2:
        raise ValueError(
            f"cond branches returned different structures: {treedef} vs "
            f"{treedef2} (reference requires matching nest structures)")
    out = []
    for t, o in zip(t_leaves, o_leaves):
        out.append(apply_op(
            "cond_select",
            lambda p, a, b: jnp.where(p.astype(bool), a, b), pred, t, o))
    return jax.tree_util.tree_unflatten(treedef, out)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Parity: paddle.static.nn.cond (control_flow.py). Both fns take no
    arguments and close over the enclosing scope."""
    if not _is_tracer(pred):
        fn = true_fn if _as_bool(pred) else false_fn
        return fn() if fn is not None else None
    taken = true_fn() if true_fn is not None else None
    other = false_fn() if false_fn is not None else None
    if taken is None or other is None:
        raise ValueError(
            "cond with a traced predicate needs BOTH branches (a one-armed "
            "if has no value to select on the untaken side)")
    return _select_trees(pred, taken, other)


def case(pred_fn_pairs, default=None, name=None):
    """Parity: static.nn.case — first true predicate wins."""
    if not pred_fn_pairs:
        return default() if default else None
    (pred, fn), rest = pred_fn_pairs[0], pred_fn_pairs[1:]
    if not _is_tracer(pred):
        if _as_bool(pred):
            return fn()
        return case(rest, default, name)
    return cond(pred, fn, lambda: case(rest, default, name))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Parity: static.nn.switch_case — dispatch on an integer index."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        pairs = sorted((int(i), f) for i, f in branch_fns)
    else:
        pairs = list(enumerate(branch_fns))
    if not _is_tracer(branch_index):
        idx = int(np.asarray(getattr(branch_index, "_data",
                                     branch_index)).reshape(()))
        for i, f in pairs:
            if i == idx:
                return f()
        return default() if default else None
    preds = [(apply_op("eq_index",
                       lambda b, i=i: (b == i).reshape(()), branch_index), f)
             for i, f in pairs]
    return case(preds, default, name)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Parity: static.nn.while_loop. Concrete condition: a taped python
    loop (fully differentiable — the unrolled reverse is the reference's
    While grad). Traced condition: lax.while_loop over the array leaves;
    forward-only (outputs carry stop_gradient=True), matching the
    reference static While's heavily restricted backward."""
    loop_vars = list(loop_vars)
    first = cond_fn(*loop_vars)
    if not _is_tracer(first) and not any(map(_is_tracer, loop_vars)):
        keep = _as_bool(first)
        while keep:
            out = body_fn(*loop_vars)
            loop_vars = list(out) if isinstance(out, (list, tuple)) else [out]
            keep = _as_bool(cond_fn(*loop_vars))
        return loop_vars

    from ..core import autograd

    leaves, treedef = jax.tree_util.tree_flatten(
        loop_vars, is_leaf=lambda x: isinstance(x, Tensor))
    arrs = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
            for l in leaves]

    def wrap(arrays):
        ts = [Tensor(a) for a in arrays]
        return jax.tree_util.tree_unflatten(treedef, ts)

    def c(arrays):
        with autograd.no_grad():
            r = cond_fn(*wrap(list(arrays)))
        return getattr(r, "_data", r).reshape(()).astype(bool)

    def b(arrays):
        with autograd.no_grad():
            out = body_fn(*wrap(list(arrays)))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        out_leaves, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        return [getattr(o, "_data", o) for o in out_leaves]

    final = jax.lax.while_loop(c, b, arrs)
    return jax.tree_util.tree_unflatten(
        treedef, [Tensor(a) for a in final])


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Parity: static.nn.static_pylayer — custom forward with an optional
    custom backward, over the autograd PyLayer machinery."""
    if backward_fn is None:
        from ..core import autograd
        with autograd.no_grad():
            return forward_fn(*inputs)
    from ..autograd import PyLayer

    class _StaticPy(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            return forward_fn(*xs)

        @staticmethod
        def backward(ctx, *grads):
            return backward_fn(*grads)

    return _StaticPy.apply(*inputs)


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """Parity: static.nn.py_func — run host python inside the program.
    Eager: call directly on numpy views. Traced: jax.pure_callback with
    `out` as the shape/dtype template (required under tracing)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    arrs = [getattr(t, "_data", t) for t in xs]
    if not any(isinstance(a, jax.core.Tracer) for a in arrs):
        res = func(*[np.asarray(a) for a in arrs])
        if res is None:
            return out
        res_list = res if isinstance(res, (list, tuple)) else [res]
        wrapped = [Tensor(jnp.asarray(np.asarray(r))) for r in res_list]
        return wrapped if len(wrapped) > 1 else wrapped[0]
    if out is None:
        raise ValueError("py_func under tracing needs `out` (a template "
                         "Tensor) for the result shape/dtype")
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(t.shape), t.dtype) for t in outs]
    res = jax.pure_callback(
        lambda *a: func(*[np.asarray(x) for x in a]),
        shapes if len(shapes) > 1 else shapes[0], *arrs)
    res_list = res if isinstance(res, (list, tuple)) else [res]
    wrapped = [Tensor(r) for r in res_list]
    return wrapped if len(wrapped) > 1 else wrapped[0]


# ------------------------------------------------------- layer builders
def _param(name, shape, dtype="float32", is_bias=False, initializer=None):
    """Create (or reuse, when named) a parameter in the global scope —
    the static-graph convention of building weights at layer-call time."""
    from . import global_scope, create_parameter
    from ..nn.initializer import Constant
    scope = global_scope()
    if name is not None and name in scope.vars:
        return scope.vars[name]
    if initializer == "ones":
        initializer = Constant(1.0)
    elif initializer == "zeros":
        initializer = Constant(0.0)
    elif isinstance(initializer, (int, float)):
        initializer = Constant(float(initializer))
    p = create_parameter(shape, dtype, is_bias=is_bias,
                         default_initializer=initializer)
    if name is not None:
        scope.vars[name] = p
        p.name = name
    return p


def _maybe_act(out, act):
    if act is None:
        return out
    from ..nn import functional as F
    return getattr(F, act)(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Parity: static.nn.fc — flatten trailing dims, create W/b inline."""
    from ..nn import functional as F
    shape = list(x.shape)
    nfd = num_flatten_dims if num_flatten_dims > 0 else len(shape) - 1
    in_dim = int(np.prod(shape[nfd:]))
    x2 = x.reshape(shape[:nfd] + [in_dim])
    w = _param(f"{name}.w_0" if name else None, (in_dim, size))
    b = None if bias_attr is False else _param(
        f"{name}.b_0" if name else None, (size,), is_bias=True)
    return _maybe_act(F.linear(x2, w, b), activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from ..nn import functional as F
    name = getattr(param_attr, "name", None)
    w = _param(name, tuple(size), dtype)
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """Parity: static.nn.sparse_embedding (PS large-scale table) — on TPU
    the table is a dense sharded parameter; lookup is identical."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def _conv(ndim, transpose, input, num_filters, filter_size, stride=1,
          padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None,
          act=None, data_format=None, name=None, output_size=None):
    from ..nn import functional as F
    data_format = data_format or ("NCHW" if ndim == 2 else "NCDHW")
    c_ax = 1 if data_format[1] == "C" else -1
    cin = int(input.shape[c_ax])
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * ndim
    if transpose:
        wshape = (cin, num_filters // groups, *ks)
    else:
        wshape = (num_filters, cin // groups, *ks)
    w = _param(f"{name}.w_0" if name else None, wshape)
    b = None if bias_attr is False else _param(
        f"{name}.b_0" if name else None, (num_filters,), is_bias=True)
    fn = {(2, False): F.conv2d, (2, True): F.conv2d_transpose,
          (3, False): F.conv3d, (3, True): F.conv3d_transpose}[
              (ndim, transpose)]
    kw = dict(stride=stride, padding=padding, dilation=dilation,
              groups=groups, data_format=data_format)
    if transpose and output_size is not None:
        kw["output_size"] = output_size
    return _maybe_act(fn(input, w, b, **kw), act)


conv2d = functools.partial(_conv, 2, False)
conv2d_transpose = functools.partial(_conv, 2, True)
conv3d = functools.partial(_conv, 3, False)
conv3d_transpose = functools.partial(_conv, 3, True)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ..nn import functional as F
    c_ax = 1 if data_layout[1] == "C" else -1
    c = int(input.shape[c_ax])
    scale = _param(f"{name}.w_0" if name else None, (c,),
                   initializer="ones")
    bias = _param(f"{name}.b_0" if name else None, (c,), is_bias=True)
    mean = _param(moving_mean_name, (c,), initializer="zeros")
    var = _param(moving_variance_name, (c,), initializer="ones")
    mean.stop_gradient = var.stop_gradient = True
    out = F.batch_norm(input, mean, var, scale, bias,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout,
                       use_global_stats=use_global_stats or None)
    return _maybe_act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import functional as F
    norm_shape = [int(s) for s in input.shape[begin_norm_axis:]]
    w = _param(f"{name}.w_0" if name else None, norm_shape,
               initializer="ones") if scale else None
    b = _param(f"{name}.b_0" if name else None, norm_shape,
               is_bias=True) if shift else None
    return _maybe_act(
        F.layer_norm(input, norm_shape, weight=w, bias=b, epsilon=epsilon),
        act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn import functional as F
    c_ax = 1 if data_layout[1] == "C" else -1
    c = int(input.shape[c_ax])
    w = _param(f"{name}.w_0" if name else None, (c,), initializer="ones")
    b = _param(f"{name}.b_0" if name else None, (c,), is_bias=True)
    return _maybe_act(F.group_norm(input, groups, epsilon=epsilon,
                                   weight=w, bias=b,
                                   data_format=data_layout), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import functional as F
    c = int(input.shape[1])
    w = _param(f"{name}.w_0" if name else None, (c,), initializer="ones")
    b = _param(f"{name}.b_0" if name else None, (c,), is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """Parity: static.nn.data_norm — normalization by ACCUMULATED batch
    statistics (batch_size/batch_sum/batch_square_sum), the CTR-model
    normalizer. Accumulators update eagerly during training calls."""
    c = int(input.shape[-1] if data_layout[-1] == "C" else input.shape[1])
    bsize = _param(f"{name}.batch_size" if name else None, (c,),
                   initializer="ones")
    bsum = _param(f"{name}.batch_sum" if name else None, (c,),
                  initializer="zeros")
    bsq = _param(f"{name}.batch_square_sum" if name else None, (c,),
                 initializer="ones")
    for t in (bsize, bsum, bsq):
        t.stop_gradient = True
    mean = bsum / bsize
    # reference kernel math (ipu/popart_canonicalization/nn_ops.cc:734-753
    # data_norm_handler): scale = sqrt(BatchSize / BatchSquareSum) — the
    # accumulated second moment is used directly, NO mean^2 subtraction
    # (ADVICE r3: the previous variance-corrected form diverged once
    # batch_sum accumulated)
    scale = (bsize / bsq).sqrt()
    out = (input - mean) * scale
    if enable_scale_and_shift:
        sw = _param(f"{name}.scale_w" if name else None, (c,),
                    initializer="ones")
        sb = _param(f"{name}.bias" if name else None, (c,), is_bias=True)
        out = out * sw + sb
    if not _is_tracer(input):
        n = float(input.shape[0])
        x = input.detach()
        red = tuple(range(x._data.ndim - 1)) if data_layout[-1] == "C" \
            else (0,) + tuple(range(2, x._data.ndim))
        r = summary_decay_rate
        bsize._data = bsize._data * r + n
        bsum._data = bsum._data * r + jnp.sum(x._data, axis=red)
        bsq._data = bsq._data * r + jnp.sum(x._data ** 2, axis=red)
    return _maybe_act(out, act)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  modulated=True, name=None):
    from ..vision.ops import deform_conv2d as _dc
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 2
    cin = int(input.shape[1])
    w = _param(f"{name}.w_0" if name else None,
               (num_filters, cin // groups, *ks))
    b = None if bias_attr is False else _param(
        f"{name}.b_0" if name else None, (num_filters,), is_bias=True)
    return _dc(input, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask if modulated else None)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ..nn import functional as F
    w = _param(f"{name}.w_0" if name else None,
               (size, int(x.shape[-1]), int(y.shape[-1])))
    b = None if bias_attr is False else _param(
        f"{name}.b_0" if name else None, (size,), is_bias=True)
    return _maybe_act(F.bilinear(x, y, w, b), act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from ..nn import functional as F
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(x.shape[1 if data_format[1] == "C" else -1]),)
    else:                     # element
        shape = tuple(int(s) for s in x.shape[1:])
    w = _param(f"{name}.w_0" if name else None, shape,
               initializer=0.25)
    return F.prelu(x, w, data_format=data_format)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn import functional as F
    return F.spectral_norm(weight, dim=dim, power_iters=power_iters,
                           eps=eps)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Parity: static.nn.nce — noise-contrastive estimation loss with
    sampled negatives (phi nce kernel). Uniform/log-uniform samplers;
    returns per-example loss (B, 1)."""
    from ..framework import random as _random
    d = int(input.shape[-1])
    w = _param(f"{name}.w_0" if name else None, (num_total_classes, d))
    b = _param(f"{name}.b_0" if name else None, (num_total_classes,),
               is_bias=True)
    B = int(input.shape[0])
    key = _random.default_rng().next_key()
    if sampler == "log_uniform":
        u = jax.random.uniform(key, (num_neg_samples,))
        neg = (jnp.exp(u * jnp.log(float(num_total_classes + 1))) - 1)
        neg = jnp.clip(neg.astype(jnp.int32), 0, num_total_classes - 1)
    elif sampler == "custom_dist" and custom_dist is not None:
        p = jnp.asarray(custom_dist, jnp.float32)
        neg = jax.random.choice(key, num_total_classes, (num_neg_samples,),
                                p=p / p.sum())
    else:
        neg = jax.random.randint(key, (num_neg_samples,), 0,
                                 num_total_classes)
    neg_t = Tensor(neg)

    def _f(x, lw, lb, lab, negs):
        lab = lab.reshape(B).astype(jnp.int32)
        pos_logit = jnp.einsum("bd,bd->b", x, lw[lab]) + lb[lab]
        neg_logit = x @ lw[negs].T + lb[negs]          # (B, num_neg)
        pos_loss = jax.nn.softplus(-pos_logit)         # -log sigmoid(s+)
        neg_loss = jax.nn.softplus(neg_logit).sum(-1)  # -log sigmoid(-s-)
        return (pos_loss + neg_loss).reshape(B, 1)

    return apply_op("nce", _f, input, w, b, label, neg_t)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Parity: static.nn.row_conv — lookahead row convolution over
    (B, T, D): out[t] = sum_{i=0..k} x[t+i] * w[i] (phi row_conv)."""
    d = int(input.shape[-1])
    k = int(future_context_size)
    w = _param(getattr(param_attr, "name", None), (k + 1, d))

    def _f(x, ww):
        pad = jnp.pad(x, ((0, 0), (0, k), (0, 0)))
        out = sum(pad[:, i:i + x.shape[1]] * ww[i] for i in range(k + 1))
        return out

    return _maybe_act(apply_op("row_conv", _f, input, w), act)


# ------------------------------------------------- sequence ops (padded)
def _time_mask(x, seq_lens):
    if seq_lens is None:
        return None
    ln = getattr(seq_lens, "_data", jnp.asarray(seq_lens))
    return jnp.arange(x.shape[1])[None, :] < ln[:, None]


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Time-axis conv over padded (B, T, D) (sequence_lod.py analog)."""
    from ..nn import functional as F
    d = int(input.shape[-1])
    w = _param(f"{name}.w_0" if name else None,
               (num_filters, d, int(filter_size)))
    b = None if bias_attr is False else _param(
        f"{name}.b_0" if name else None, (num_filters,), is_bias=True)
    x = input.transpose([0, 2, 1])                 # (B, D, T)
    start = -((filter_size - 1) // 2) if padding_start is None \
        else padding_start
    pad_left = max(-start, 0)
    pad_right = max(filter_size - 1 - pad_left, 0)

    def _f(xa, wa, ba):
        xa = jnp.pad(xa, ((0, 0), (0, 0), (pad_left, pad_right)))
        out = jax.lax.conv_general_dilated(
            xa, wa, (filter_stride,), "VALID",
            dimension_numbers=("NCH", "OIH", "NCH"))
        if ba is not None:
            out = out + ba[None, :, None]
        return out

    out = apply_op("sequence_conv", _f, x, w, b).transpose([0, 2, 1])
    return _maybe_act(out, act)


def sequence_softmax(input, use_cudnn=False, name=None, seq_lens=None):
    def _f(x):
        m = _time_mask(input, seq_lens)
        if m is not None:
            x = jnp.where(m[..., None] if x.ndim == 3 else m, x, -1e9)
        return jax.nn.softmax(x, axis=1)
    return apply_op("sequence_softmax", _f, input)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  seq_lens=None):
    def _f(x):
        m = _time_mask(input, seq_lens)
        mask = None if m is None else m[..., None].astype(x.dtype)
        if pool_type.lower() == "sum":
            return (x if mask is None else x * mask).sum(axis=1)
        if pool_type.lower() in ("average", "mean"):
            if mask is None:
                return x.mean(axis=1)
            return (x * mask).sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1)
        if pool_type.lower() == "sqrt":
            n = x.shape[1] if mask is None else mask.sum(axis=1)
            return (x if mask is None else x * mask).sum(axis=1) \
                / jnp.sqrt(jnp.maximum(n, 1))
        if pool_type.lower() == "max":
            if mask is None:
                return x.max(axis=1)
            return jnp.where(mask.astype(bool), x, -jnp.inf).max(axis=1)
        if pool_type.lower() == "first":
            return x[:, 0]
        if pool_type.lower() == "last":
            if seq_lens is None:
                return x[:, -1]
            ln = getattr(seq_lens, "_data", jnp.asarray(seq_lens))
            return jnp.take_along_axis(
                x, (ln - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")
    return apply_op("sequence_pool", _f, input)


def sequence_first_step(input, seq_lens=None):
    return sequence_pool(input, "first", seq_lens=seq_lens)


def sequence_last_step(input, seq_lens=None):
    return sequence_pool(input, "last", seq_lens=seq_lens)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Padded analog of LoD sequence_expand: tile x's rows to match y's
    time dimension (each x row broadcast along y's T)."""
    def _f(xa, ya):
        t = ya.shape[1]
        return jnp.repeat(xa[:, None], t, axis=1).reshape(
            (xa.shape[0] * t,) + xa.shape[1:])
    return apply_op("sequence_expand", _f, x, y)
