"""Search / sort / sampling-adjacent ops.

Parity: reference `python/paddle/tensor/search.py`. Ops with data-dependent
output shapes (nonzero, unique, masked_select) are eager-only — under
`to_static`/jit the reference has the same restriction via shape inference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import apply_op, def_op

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "searchsorted", "nonzero",
    "kthvalue", "mode", "unique", "unique_consecutive", "index_sample",
    "bucketize", "top_p_sampling",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _f(a):
        out = jnp.argmax(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(d)
    return apply_op("argmax", _f, x,
                    op_attrs={"axis": None if axis is None else int(axis),
                              "keepdim": keepdim if axis is not None
                              else False})


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _f(a):
        out = jnp.argmin(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(d)
    return apply_op("argmin", _f, x,
                    op_attrs={"axis": None if axis is None else int(axis),
                              "keepdim": keepdim if axis is not None
                              else False})


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def _f(a):
        idx = jnp.argsort(a, axis=int(axis), stable=True,
                          descending=descending)
        return idx.astype(jnp.int64)
    return apply_op("argsort", _f, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def _f(a):
        out = jnp.sort(a, axis=int(axis), stable=True, descending=descending)
        return out
    return apply_op("sort", _f, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(k._data) if isinstance(k, Tensor) else int(k)
    def _f(a):
        ax = -1 if axis is None else int(axis)
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply_op("topk", _f, x)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    def _f(seq, v):
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            flat_seq = seq.reshape(-1, seq.shape[-1])
            flat_v = v.reshape(-1, v.shape[-1])
            out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(flat_seq, flat_v)
            out = out.reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply_op("searchsorted", _f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def nonzero(x, as_tuple=False):
    # dynamic shape: eager-only (same restriction as reference static mode)
    arr = np.asarray(x._data)
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    k = int(k)
    def _f(a):
        ax = int(axis) % a.ndim
        sorted_vals = jnp.sort(a, axis=ax)
        sorted_idx = jnp.argsort(a, axis=ax)
        vals = jnp.take(sorted_vals, k - 1, axis=ax)
        idx = jnp.take(sorted_idx, k - 1, axis=ax)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        return vals, idx.astype(jnp.int64)
    return apply_op("kthvalue", _f, x)


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(x._data)
    ax = int(axis) % arr.ndim
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts[counts == counts.max()].size and counts)]
        # paddle: the largest value among the most frequent
        maxc = counts.max()
        best = uniq[counts == maxc].max()
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    out_shape = moved.shape[:-1]
    v = vals.reshape(out_shape)
    ii = idxs.reshape(out_shape)
    if keepdim:
        v = np.expand_dims(v, ax)
        ii = np.expand_dims(ii, ax)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(ii))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    arr = np.asarray(x._data)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = [Tensor(jnp.asarray(r if i == 0 else r.astype(np.int64)))
            for i, r in enumerate(res)]
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        flat = arr.reshape(-1)
        if flat.size == 0:
            keep = np.zeros(0, dtype=bool)
        else:
            keep = np.concatenate([[True], flat[1:] != flat[:-1]])
        out = flat[keep]
        outs = [Tensor(jnp.asarray(out))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
        if return_counts:
            pos = np.where(keep)[0]
            counts = np.diff(np.concatenate([pos, [flat.size]]))
            outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis not supported yet")


@def_op("index_sample")
def index_sample(x, index):
    rows = jnp.arange(x.shape[0])[:, None]
    return x[rows, index]


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling over per-row probability vectors.

    Parity: `python/paddle/tensor/search.py:1363` (`phi` kernel
    `top_p_sampling`). x: (B, V) probabilities; ps: (B,) per-row top-p.
    Returns (values (B, 1), ids (B, 1) int64); with return_top also the
    top-k (values, ids). Both modes sample within the (nucleus AND
    threshold) candidate set — the reference's non-truncated kernel keeps
    that restriction too and only changes the within-prefix sampling
    rule, which after normalization coincides with the truncated rule.
    TPU-native: a full descending sort + cumsum + categorical draw — one
    fused XLA program, no host sync; dispatched through apply_op so the
    profiler/NaN-check hooks see it.
    """
    from ..framework.random import rng_key

    if seed is not None and int(seed) >= 0:
        key = jax.random.PRNGKey(int(seed))
    else:
        key = rng_key()
    kk = max(int(k), 1)

    def _f(probs, p_row, *rest):
        rest = list(rest)
        th = rest.pop(0) if threshold is not None else None
        rows = rest.pop(0) if topp_seed is not None else None
        B, V = probs.shape
        pf = probs.astype(jnp.float32)
        p_row = p_row.reshape(-1, 1).astype(jnp.float32)
        sorted_p, sorted_idx = jax.lax.top_k(pf, V)
        cum = jnp.cumsum(sorted_p, axis=-1)
        # keep the minimal prefix whose mass reaches ps (mass *before*
        # the token < ps keeps the boundary token; top-1 always survives)
        keep = (cum - sorted_p) < p_row
        if th is not None:
            keep = jnp.logical_and(
                keep, sorted_p >= th.reshape(-1, 1).astype(jnp.float32))
        keep = keep.at[:, 0].set(True)
        masked = jnp.where(keep, sorted_p, 0.0)
        logits = jnp.log(jnp.maximum(masked, 1e-30))
        logits = jnp.where(masked > 0, logits, -jnp.inf)
        if rows is not None:
            keys = jax.vmap(lambda s: jax.random.fold_in(key, s))(
                rows.reshape(-1).astype(jnp.uint32))
            pos = jax.vmap(lambda kr, lg: jax.random.categorical(kr, lg))(
                keys, logits)
        else:
            pos = jax.random.categorical(key, logits, axis=-1)
        pos = pos[:, None]
        ids = jnp.take_along_axis(sorted_idx, pos, axis=1).astype(jnp.int64)
        vals = jnp.take_along_axis(sorted_p, pos, axis=1).astype(probs.dtype)
        if return_top:
            # the full sort is already here — slice it instead of a
            # second top_k pass
            return (vals, ids, sorted_p[:, :kk].astype(probs.dtype),
                    sorted_idx[:, :kk].astype(jnp.int64))
        return vals, ids

    def _t(v):
        return v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))

    args = [_t(x), _t(ps)]
    if threshold is not None:
        args.append(_t(threshold))
    if topp_seed is not None:
        args.append(_t(topp_seed))
    return apply_op("top_p_sampling", _f, *args)
