"""Random sampling ops, driven by the global splittable PRNG stream.

Parity: reference `python/paddle/tensor/random.py` (uniform/gaussian/
randint/randperm/bernoulli/multinomial/...).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor
from ..framework.random import rng_key
from .creation import _shape_list

__all__ = [
    "rand", "randn", "normal", "standard_normal", "uniform", "randint",
    "randint_like", "randperm", "bernoulli", "multinomial", "poisson",
    "exponential_", "uniform_", "normal_", "standard_gamma", "binomial",
    "log_normal", "cauchy_", "geometric_",
]


def rand(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(rng_key(), _shape_list(shape), d))


def randn(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.normal(rng_key(), _shape_list(shape), d))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else tuple(_shape_list(shape))
        z = jax.random.normal(rng_key(), out_shape, get_default_dtype())
        return Tensor(m + s * z)
    sh = _shape_list(shape) if shape is not None else []
    z = jax.random.normal(rng_key(), sh, get_default_dtype())
    return Tensor(mean + std * z)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(rng_key(), _shape_list(shape), d,
                                     minval=min, maxval=max))


def _reset_history(x):
    """In-place randomization severs the op history: the new values do
    not depend on whatever produced the old ones."""
    x._grad_node = None
    x._grad_out_idx = None
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(rng_key(), tuple(x._data.shape), x.dtype,
                                 minval=min, maxval=max)
    return _reset_history(x)


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = mean + std * jax.random.normal(rng_key(), tuple(x._data.shape), x.dtype)
    return _reset_history(x)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype)
    return Tensor(jax.random.randint(rng_key(), _shape_list(shape), int(low), int(high), d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(rng_key(), tuple(x._data.shape), int(low), int(high), d))


def randperm(n, dtype="int64", name=None):
    d = convert_dtype(dtype)
    return Tensor(jax.random.permutation(rng_key(), int(n)).astype(d))


def bernoulli(x, name=None):
    p = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(rng_key(), p).astype(p.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(rng_key(), p, tuple(x._data.shape)).astype(x.dtype)
    return x


def multinomial(x, num_samples=1, replacement=False, name=None):
    p = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(rng_key(), logits, axis=-1,
                                     shape=(num_samples,) + p.shape[:-1])
        out = jnp.moveaxis(out, 0, -1) if p.ndim > 1 else out
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(rng_key(), p.shape, logits.dtype)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def poisson(x, name=None):
    lam = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(rng_key(), lam).astype(lam.dtype))


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(rng_key(), tuple(x._data.shape), x.dtype,
                           minval=jnp.finfo(x.dtype).tiny, maxval=1.0)
    x._data = -jnp.log(u) / lam
    return _reset_history(x)


def standard_gamma(x, name=None):
    alpha = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.gamma(rng_key(), alpha))


def binomial(count, prob, name=None):
    n = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(rng_key(), n.astype(jnp.float32),
                                      p).astype(jnp.int64))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    sh = _shape_list(shape) if shape is not None else []
    z = jax.random.normal(rng_key(), sh, get_default_dtype())
    return Tensor(jnp.exp(mean + std * z))


def cauchy_(x, loc=0, scale=1, name=None):
    u = jax.random.uniform(rng_key(), tuple(x._data.shape), x.dtype,
                           minval=1e-7, maxval=1.0 - 1e-7)
    x._data = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
    return x


def geometric_(x, probs, name=None):
    u = jax.random.uniform(rng_key(), tuple(x._data.shape), jnp.float32,
                           minval=1e-7, maxval=1.0)
    x._data = (jnp.ceil(jnp.log(u) / jnp.log1p(-probs))).astype(x.dtype)
    return x
