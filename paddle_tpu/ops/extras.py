"""Op-coverage tail: the remaining reference tensor-API functions.

Parity: assorted functions from `python/paddle/tensor/{math,manipulation,
linalg,search,stat,attribute,random,creation}.py` not covered by the core
op modules, plus the full in-place (`op_`) variant table (generated in
methods.py against these and the existing ops)."""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import apply_op

__all__ = [
    "add_n", "cartesian_prod", "diagonal", "inverse", "isin", "isneginf",
    "isposinf", "multiplex", "gammainc", "gammaincc",
    "block_diag", "diagonal_scatter", "fill_diagonal_",
    "fill_diagonal_tensor", "index_fill", "masked_scatter", "shard_index",
    "slice_scatter", "tensor_split", "as_strided",
    "cholesky_inverse", "histogram_bin_edges", "matrix_exp", "svd_lowrank",
    "pca_lowrank",
    "top_p_sampling", "quantile", "nanquantile", "numel",
    "is_complex", "is_floating_point", "is_integer", "rank",
    "gaussian", "fill_constant", "sigmoid", "reduce_as", "create_tensor",
    "create_global_var",
]


# ------------------------------------------------------------------- math
def add_n(inputs, name=None):
    """Sum a list of tensors. Parity: math.add_n."""
    if isinstance(inputs, Tensor):
        return inputs
    return apply_op("add_n", lambda xs: sum(xs[1:], xs[0]), list(inputs))


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors. Parity: math.cartesian_prod."""
    xs = x if isinstance(x, (list, tuple)) else [x]

    def _f(arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply_op("cartesian_prod", _f, list(xs))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal",
                    lambda a: jnp.diagonal(a, offset, axis1, axis2), x)


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply_op("isin",
                    lambda a, t: jnp.isin(a, t, invert=invert), x, test_x)


def isneginf(x, name=None):
    return apply_op("isneginf", jnp.isneginf, x)


def isposinf(x, name=None):
    return apply_op("isposinf", jnp.isposinf, x)


def multiplex(inputs, index, name=None):
    """Row-wise select between candidate tensors. Parity: math.multiplex."""
    def _f(xs, idx):
        stacked = jnp.stack(xs, axis=0)            # (n, B, ...)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]
    return apply_op("multiplex", _f, list(inputs), index)


def gammainc(x, y, name=None):
    from jax.scipy.special import gammainc as gi
    return apply_op("gammainc", gi, x, y)


def gammaincc(x, y, name=None):
    from jax.scipy.special import gammaincc as gic
    return apply_op("gammaincc", gic, x, y)


# ----------------------------------------------------------- manipulation
def block_diag(inputs, name=None):
    def _f(xs):
        return jax.scipy.linalg.block_diag(*xs)
    return apply_op("block_diag", _f, list(inputs))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def _f(a, b):
        n = min(a.shape[axis1], a.shape[axis2])
        i = jnp.arange(b.shape[-1] if b.ndim else n)
        sel = [slice(None)] * a.ndim
        sel[axis1] = i - min(offset, 0)
        sel[axis2] = i + max(offset, 0)
        return a.at[tuple(sel)].set(b)
    return apply_op("diagonal_scatter", _f, x, y)


def _diag_len(rows, cols, offset):
    # length of the offset diagonal of a rows x cols matrix
    return max(0, min(rows + min(offset, 0), cols - max(offset, 0)))


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    def _f(a):
        i = jnp.arange(_diag_len(a.shape[-2], a.shape[-1], offset))
        rows = i - min(offset, 0)
        cols = i + max(offset, 0)
        return a.at[..., rows, cols].set(value)
    out = apply_op("fill_diagonal_", _f, x)
    x._data = out._data
    x._grad_node = out._grad_node
    x._grad_out_idx = out._grad_out_idx
    x.stop_gradient = out.stop_gradient
    return x


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    def _f(a, b):
        i = jnp.arange(_diag_len(a.shape[dim1], a.shape[dim2], offset))
        rows = i - min(offset, 0)
        cols = i + max(offset, 0)
        sel = [slice(None)] * a.ndim
        sel[dim1] = rows
        sel[dim2] = cols
        return a.at[tuple(sel)].set(b)
    return apply_op("fill_diagonal_tensor", _f, x, y)


def index_fill(x, index, axis, value, name=None):
    def _f(a, idx):
        sel = [slice(None)] * a.ndim
        sel[axis] = idx
        return a.at[tuple(sel)].set(value)
    return apply_op("index_fill", _f, x, index)


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions with consecutive values (parity:
    manipulation.masked_scatter)."""
    def _f(a, m, v):
        m = jnp.broadcast_to(m, a.shape)
        flatv = v.reshape(-1)
        # k-th True gets flatv[k]
        order = jnp.cumsum(m.reshape(-1)) - 1
        take = jnp.clip(order, 0, flatv.shape[0] - 1)
        filled = jnp.where(m.reshape(-1), flatv[take], a.reshape(-1))
        return filled.reshape(a.shape)
    return apply_op("masked_scatter", _f, x, mask, value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Parity: manipulation.shard_index (vocab-shard relabeling)."""
    def _f(a):
        per = (index_num + nshards - 1) // nshards
        lo = shard_id * per
        inside = (a >= lo) & (a < lo + per)
        return jnp.where(inside, a - lo, ignore_value)
    return apply_op("shard_index", _f, input)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def _f(a, v):
        sel = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sel[ax] = slice(s, e, st)
        return a.at[tuple(sel)].set(v)
    return apply_op("slice_scatter", _f, x, value)


def tensor_split(x, num_or_indices, axis=0, name=None):
    def _f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=axis))
    return list(apply_op("tensor_split", _f, x))


def as_strided(x, shape, stride, offset=0, name=None):
    """View with explicit strides (materialized via gather — XLA has no
    aliasing views). Parity: manipulation.as_strided."""
    def _f(a):
        flat = a.reshape(-1)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
        lin = sum((g * st for g, st in zip(grids, stride)),
                  jnp.zeros((), jnp.int32)) + offset
        return flat[lin.astype(jnp.int32)]
    return apply_op("as_strided", _f, x)


# ----------------------------------------------------------------- linalg
def cholesky_inverse(x, upper=False, name=None):
    def _f(a):
        ident = jnp.eye(a.shape[-1], dtype=a.dtype)
        inv_factor = jax.scipy.linalg.solve_triangular(a, ident, lower=not upper)
        return inv_factor.T @ inv_factor if not upper else \
            inv_factor @ inv_factor.T
    return apply_op("cholesky_inverse", _f, x)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    def _f(a):
        lo, hi = (jnp.min(a), jnp.max(a)) if min == 0 and max == 0 \
            else (min, max)
        return jnp.linspace(lo, hi, bins + 1)
    return apply_op("histogram_bin_edges", _f, input)


def matrix_exp(x, name=None):
    return apply_op("matrix_exp", jax.scipy.linalg.expm, x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD of (x - M) (parity: linalg.svd_lowrank)."""
    from ..framework.random import rng_key
    key = rng_key()

    def _f(a, *rest):
        if rest:
            a = a - rest[0]
        m, n = a.shape[-2:]
        r = min(q, m, n)
        omega = jax.random.normal(key, a.shape[:-2] + (n, r), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (jnp.swapaxes(a, -1, -2) @ y)
        Q, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(Q, -1, -2) @ a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return Q @ u, s, jnp.swapaxes(vh, -1, -2)
    if M is not None:
        return apply_op("svd_lowrank", _f, x, M)
    return apply_op("svd_lowrank", _f, x)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def _f(a):
        k = q if q is not None else min(6, *a.shape[-2:])
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vh = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :k], s[..., :k], jnp.swapaxes(vh, -1, -2)[..., :k]
    return apply_op("pca_lowrank", _f, x)


# ----------------------------------------------------------------- search
def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """Nucleus sampling (parity: phi top_p_sampling). One implementation
    lives in ops/search.py; this alias keeps the historical extras export
    pointing at the same function so paddle.top_p_sampling ==
    paddle.tensor.top_p_sampling."""
    from .search import top_p_sampling as _impl
    return _impl(x, ps, threshold=threshold, topp_seed=topp_seed, seed=seed,
                 k=k, mode=mode, return_top=return_top, name=name)


# ------------------------------------------------------------------- stat
def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    return apply_op(
        "quantile",
        lambda a: jnp.quantile(a, jnp.asarray(q), axis=axis,
                               keepdims=keepdim, method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return apply_op(
        "nanquantile",
        lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=axis,
                                  keepdims=keepdim, method=interpolation), x)


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1))


# -------------------------------------------------------------- attribute
def is_complex(x):
    return jnp.issubdtype(x._data.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x._data.dtype, jnp.integer)


def rank(input):
    return Tensor(jnp.asarray(input._data.ndim))


# ----------------------------------------------------- random / creation
def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    from ..framework.random import rng_key
    from ..core.dtype import convert_dtype
    key = rng_key() if seed == 0 else jax.random.key(seed)
    dt = jnp.dtype(convert_dtype(dtype) or "float32")
    return Tensor(mean + std * jax.random.normal(key, tuple(shape), dt))


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    from .creation import full
    t = full(shape, value, dtype=dtype)
    if out is not None:
        out._data = t._data
        return out
    return t


def sigmoid(x, name=None):
    return apply_op("sigmoid", jax.nn.sigmoid, x)


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (parity: math.reduce_as)."""
    def _f(a, t):
        extra = a.ndim - t.ndim
        axes = tuple(range(extra)) + tuple(
            i + extra for i, (sa, st) in enumerate(
                zip(a.shape[extra:], t.shape)) if st == 1 and sa != 1)
        out = jnp.sum(a, axis=axes, keepdims=False)
        return out.reshape(t.shape)
    return apply_op("reduce_as", _f, x, target)


def create_tensor(dtype, name=None, persistable=False):
    """Parity: creation.create_tensor (static-graph var shell)."""
    from ..core.dtype import convert_dtype
    return Tensor(jnp.zeros((), jnp.dtype(convert_dtype(dtype) or "float32")))


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Parity: creation.create_global_var."""
    return fill_constant(shape, dtype, value)
