"""Comparison / logical / bitwise ops.

Parity: reference `python/paddle/tensor/logic.py`.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import apply_op

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "bitwise_left_shift", "bitwise_right_shift", "is_empty", "isclose",
    "allclose", "equal_all", "isreal", "iscomplex", "is_tensor",
]


def _binary(op_name, fn):
    def op(x, y, name=None):
        return apply_op(op_name, fn, x, y)
    op.__name__ = op_name
    return op


equal = _binary("equal", lambda x, y: jnp.equal(x, y))
not_equal = _binary("not_equal", lambda x, y: jnp.not_equal(x, y))
greater_than = _binary("greater_than", lambda x, y: jnp.greater(x, y))
greater_equal = _binary("greater_equal", lambda x, y: jnp.greater_equal(x, y))
less_than = _binary("less_than", lambda x, y: jnp.less(x, y))
less_equal = _binary("less_equal", lambda x, y: jnp.less_equal(x, y))
logical_and = _binary("logical_and", lambda x, y: jnp.logical_and(x, y))
logical_or = _binary("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = _binary("logical_xor", lambda x, y: jnp.logical_xor(x, y))
bitwise_and = _binary("bitwise_and", lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = _binary("bitwise_or", lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = _binary("bitwise_xor", lambda x, y: jnp.bitwise_xor(x, y))
bitwise_left_shift = _binary("bitwise_left_shift", lambda x, y: jnp.left_shift(x, y))
bitwise_right_shift = _binary("bitwise_right_shift", lambda x, y: jnp.right_shift(x, y))


def logical_not(x, name=None):
    return apply_op("logical_not", jnp.logical_not, x)


def bitwise_not(x, name=None):
    return apply_op("bitwise_not", jnp.bitwise_not, x)


def isreal(x, name=None):
    return apply_op("isreal", jnp.isreal, x)


def iscomplex(x, name=None):
    return apply_op("iscomplex", jnp.iscomplex, x)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("isclose",
                    lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                    x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("allclose",
                    lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                    x, y)


def equal_all(x, y, name=None):
    return apply_op("equal_all", lambda a, b: jnp.array_equal(a, b), x, y)


def is_tensor(x):
    return isinstance(x, Tensor)
