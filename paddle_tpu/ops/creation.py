"""Tensor creation ops.

Parity: reference `python/paddle/tensor/creation.py` (to_tensor, zeros, ones,
full, arange, linspace, eye, empty, meshgrid, diag, tril/triu, ...).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, to_tensor
from .dispatch import apply_op, def_op

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "meshgrid", "diag", "diagflat", "diag_embed", "tril", "triu",
    "clone", "assign", "tril_indices", "triu_indices", "complex",
    "create_parameter", "ones_like", "polar",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._data)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.zeros(_shape_list(shape), d))


def ones(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.ones(_shape_list(shape), d))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        arr = jnp.full(_shape_list(shape), fill_value)
        if arr.dtype == jnp.float64:
            arr = arr.astype(get_default_dtype())
        return Tensor(arr)
    return Tensor(jnp.full(_shape_list(shape), fill_value, convert_dtype(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jnp.zeros(x._data.shape, d))


def ones_like(x, dtype=None, name=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jnp.ones(x._data.shape, d))


def full_like(x, fill_value, dtype=None, name=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jnp.full(x._data.shape, fill_value, d))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        pass
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            d = jnp.int64
        else:
            d = get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = num.item() if isinstance(num, Tensor) else num
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.linspace(start, stop, int(num), dtype=d))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base), dtype=d))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns), dtype=d))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return apply_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *args)


@def_op("diag")
def diag(x, offset=0, padding_value=0, name=None):
    if x.ndim == 1 and padding_value != 0:
        out = jnp.diag(x, k=offset)
        mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
    return jnp.diag(x, k=offset)


@def_op("diagflat")
def diagflat(x, offset=0, name=None):
    return jnp.diagflat(x, k=offset)


@def_op("diag_embed")
def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    out = base.at[..., r, c].set(x)
    # move the two new axes into (dim1, dim2)
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [i for i in range(nd - 2)]
        order = list(range(nd - 2))
        # build permutation placing last two axes at d1, d2
        perm = []
        src = list(range(nd - 2))
        for i in range(nd):
            if i == d1:
                perm.append(nd - 2)
            elif i == d2:
                perm.append(nd - 1)
            else:
                perm.append(src.pop(0))
        out = jnp.transpose(out, perm)
    return out


@def_op("tril")
def tril(x, diagonal=0, name=None):
    return jnp.tril(x, k=diagonal)


@def_op("triu")
def triu(x, diagonal=0, name=None):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), convert_dtype(dtype)))


@def_op("clone")
def clone(x, name=None):
    return x


def assign(x, output=None):
    src = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if output is None:
        return Tensor(src)
    output.copy_(src)
    return output


@def_op("complex")
def complex(real, imag, name=None):
    return jax.lax.complex(real, imag)


@def_op("polar")
def polar(abs, angle, name=None):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.initializer import _init_tensor
    d = convert_dtype(dtype) or get_default_dtype()
    t = _init_tensor(tuple(_shape_list(shape)), d, default_initializer, is_bias=is_bias)
    t.stop_gradient = False
    t._is_param = True
    return t
