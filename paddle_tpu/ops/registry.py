"""Enumerable op registry with dtype capability tables.

Parity: reference op YAML registry (`paddle/phi/ops/yaml/ops.yaml`, 465
ops + dtype tables per PD_REGISTER_KERNEL) — the single enumerable source
the reference generates everything from. Here ops are plain functions in
the `ops` modules; this registry enumerates them with category + dtype
metadata so tooling (coverage audits, doc generation, dispatch
inspection) has the same queryable surface.
"""
from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

__all__ = ["OpInfo", "registry", "get_op_list", "lookup"]

# default dtype capability sets (XLA lowers all of these on TPU; f64
# executes but is emulated/slow — kept for numeric parity tests)
_FLOAT = ("float32", "bfloat16", "float16", "float64")
_ALL = _FLOAT + ("int32", "int64", "bool")
_INT = ("int32", "int64")

_CATEGORY_DTYPES = {
    "math": _ALL,
    "creation": _ALL,
    "manipulation": _ALL,
    "linalg": _FLOAT,
    "logic": _ALL,
    "search": _ALL,
    "random": _FLOAT,
    "extras": _ALL,
}


class OpInfo(NamedTuple):
    name: str
    category: str
    fn: object
    dtypes: tuple


_cache: Optional[Dict[str, OpInfo]] = None


def registry(refresh: bool = False) -> Dict[str, OpInfo]:
    """name -> OpInfo for every exported op function."""
    global _cache
    if _cache is not None and not refresh:
        return _cache
    from . import creation, extras, linalg, logic, manipulation, math
    from . import random as random_mod
    from . import search
    table: Dict[str, OpInfo] = {}
    mods = [("math", math), ("creation", creation),
            ("manipulation", manipulation), ("linalg", linalg),
            ("logic", logic), ("search", search), ("random", random_mod),
            ("extras", extras)]
    for cat, mod in mods:
        for name in getattr(mod, "__all__", []):
            fn = getattr(mod, name, None)
            if callable(fn):
                table[name] = OpInfo(name, cat, fn, _CATEGORY_DTYPES[cat])
    # custom ops registered at runtime join the table
    try:
        from ..utils.cpp_extension import _REGISTRY as custom
        for name, fn in custom.items():
            table.setdefault(name, OpInfo(name, "custom", fn, _ALL))
    except Exception:
        pass
    _cache = table
    return table


def get_op_list(category: Optional[str] = None) -> List[str]:
    """Sorted op names (optionally one category) — the ops.yaml
    enumeration role."""
    table = registry()
    return sorted(n for n, info in table.items()
                  if category is None or info.category == category)


def lookup(name: str) -> Optional[OpInfo]:
    return registry().get(name)
