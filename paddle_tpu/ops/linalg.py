"""Linear algebra ops (matmul rides the MXU; decompositions via lax.linalg).

Parity: reference `python/paddle/tensor/linalg.py` + phi kernels
(`paddle/phi/kernels/matmul_kernel.h`, `kernels/impl/matmul_kernel_impl.h`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import apply_op, def_op

__all__ = [
    "matmul", "mm", "bmm", "mv", "dot", "t", "norm", "vector_norm",
    "matrix_norm", "dist", "cross", "cholesky", "cholesky_solve", "inv",
    "det", "slogdet", "svd", "qr", "eig", "eigh", "eigvals", "eigvalsh",
    "matrix_power", "pinv", "solve", "triangular_solve", "lstsq", "lu",
    "lu_unpack", "matrix_rank", "cond", "histogram", "histogramdd",
    "bincount", "einsum", "multi_dot", "corrcoef", "cov", "householder_product",
    "matrix_transpose", "pdist", "cdist", "svd_lowrank", "pca_lowrank", "cholesky_inverse", "matrix_exp", "ormqr", "fp8_fp8_half_gemm_fused",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op("matmul", _f, x, y)


@def_op("mm")
def mm(input, mat2, name=None):
    return jnp.matmul(input, mat2)


@def_op("bmm")
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@def_op("mv")
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@def_op("dot")
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@def_op("t")
def t(input, name=None):
    if input.ndim < 2:
        return input
    return jnp.swapaxes(input, -1, -2)


@def_op("matrix_transpose")
def matrix_transpose(x, name=None):
    return jnp.swapaxes(x, -1, -2)


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    def _f(a):
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a))))
            return jnp.linalg.norm(a, ord=None, axis=ax, keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(a, ord="nuc", axis=ax, keepdims=keepdim)
        if p == float("inf"):
            r = jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
            return r
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        flat_ax = ax
        return jnp.sum(jnp.abs(a) ** p, axis=flat_ax, keepdims=keepdim) ** (1.0 / p)
    return apply_op("norm", _f, x,
                    op_attrs={"axis": ax, "keepdim": keepdim})


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    ax = tuple(int(a) for a in axis)
    return apply_op("matrix_norm",
                    lambda a: jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim), x)


@def_op("dist")
def dist(x, y, p=2, name=None):
    d = jnp.abs(x - y)
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(d ** p) ** (1.0 / p)


@def_op("cross")
def cross(x, y, axis=9, name=None):
    ax = axis
    if ax == 9:
        # paddle default: first axis with dim 3
        ax = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return jnp.cross(x, y, axis=ax)


@def_op("cholesky")
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@def_op("cholesky_solve")
def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@def_op("inv")
def inv(x, name=None):
    return jnp.linalg.inv(x)


@def_op("det")
def det(x, name=None):
    return jnp.linalg.det(x)


@def_op("slogdet")
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@def_op("svd")
def svd(x, full_matrices=False, name=None):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@def_op("qr")
def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


def eig(x, name=None):
    # CPU-only in jax; run on host.
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


@def_op("eigh")
def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x, name=None):
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


@def_op("eigvalsh")
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@def_op("matrix_power")
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@def_op("pinv")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@def_op("solve")
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@def_op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@def_op("lstsq")
def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@def_op("lu")
def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    def _f(lu_mat, piv):
        m = lu_mat.shape[-2]
        L = jnp.tril(lu_mat, -1) + jnp.eye(m, lu_mat.shape[-1], dtype=lu_mat.dtype)
        L = L[..., :, :min(lu_mat.shape[-2:])] if lu_mat.shape[-2] > lu_mat.shape[-1] else L
        U = jnp.triu(lu_mat)[..., :min(lu_mat.shape[-2:]), :]
        perm = jnp.arange(m)
        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            p = p.at[i].set(pj).at[j].set(pi)
            return p
        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        P = jnp.eye(m, dtype=lu_mat.dtype)[perm].T
        return P, L, U
    return apply_op("lu_unpack", _f, x, y)


@def_op("matrix_rank")
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@def_op("cond")
def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def _f(a, w):
        lo, hi = float(min), float(max)
        if lo == 0 and hi == 0:
            lo, hi = float(jnp.min(a)), float(jnp.max(a))
        hist, _ = jnp.histogram(a, bins=bins, range=(lo, hi), weights=w, density=density)
        return hist if density or w is not None else hist.astype(jnp.int64)
    w = weight
    return apply_op("histogram", _f, input, w)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    arr = np.asarray(x._data)
    w = np.asarray(weights._data) if isinstance(weights, Tensor) else weights
    hist, edges = np.histogramdd(arr, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    n = int(np.asarray(x._data).max()) + 1 if x.size else 0
    length = max(n, int(minlength))
    def _f(a, w):
        out = jnp.bincount(a, weights=w, minlength=length, length=length)
        return out if w is not None else out.astype(jnp.int64)
    return apply_op("bincount", _f, x, weights)


def einsum(equation, *operands):
    return apply_op("einsum", lambda *ops: jnp.einsum(equation, *ops), *operands)


@def_op("multi_dot")
def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(list(x))


@def_op("corrcoef")
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@def_op("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@def_op("householder_product")
def householder_product(x, tau, name=None):
    m, n = x.shape[-2], x.shape[-1]
    def one(mat, t):
        q = jnp.eye(m, dtype=mat.dtype)
        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0, mat[:, i])
            v = v.at[i].set(1.0)
            h = jnp.eye(m, dtype=mat.dtype) - t[i] * jnp.outer(v, v)
            return q @ h
        q = jax.lax.fori_loop(0, n, body, q)
        return q[:, :n]
    if x.ndim == 2:
        return one(x, tau)
    batch = x.reshape((-1,) + x.shape[-2:])
    taub = tau.reshape((-1, tau.shape[-1]))
    out = jax.vmap(one)(batch, taub)
    return out.reshape(x.shape[:-2] + (m, n))


@def_op("pdist")
def pdist(x, p=2.0, name=None):
    n = x.shape[0]
    d = jnp.linalg.norm(x[:, None, :] - x[None, :, :] + 1e-30, ord=p, axis=-1)
    iu = jnp.triu_indices(n, k=1)
    return d[iu]


@def_op("cdist")
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (parity: paddle.linalg.svd_lowrank,
    `python/paddle/tensor/linalg.py`). Returns (U (m, q), S (q,),
    V (n, q)). Power iteration sharpens the spectrum; everything is
    MXU matmuls + one small exact SVD."""
    from ..framework.random import rng_key
    import jax

    def _f(a, *rest):
        m = rest[0] if M is not None else None
        if m is not None:
            a = a - m
        key = rng_key()
        n = a.shape[-1]
        omega = jax.random.normal(key, a.shape[:-2] + (n, q), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (jnp.swapaxes(a, -1, -2) @ y)
        Q, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(Q, -1, -2) @ a          # (q, n)
        u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
        u = Q @ u_b
        return u, s, jnp.swapaxes(vt, -1, -2)

    args = [x] + ([M] if M is not None else [])
    return apply_op("svd_lowrank", _f, *args)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (parity: paddle.linalg.pca_lowrank)."""
    qq = q if q is not None else min(6, *[int(s) for s in x.shape[-2:]])

    def _f(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        return a
    centered = apply_op("pca_center", _f, x)
    return svd_lowrank(centered, q=qq, niter=niter)


@def_op("cholesky_inverse")
def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (parity:
    paddle.linalg.cholesky_inverse)."""
    L = jnp.swapaxes(x, -1, -2) if upper else x
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    inv_l = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return jnp.swapaxes(inv_l, -1, -2) @ inv_l


@def_op("matrix_exp")
def matrix_exp(x, name=None):
    """Matrix exponential (parity: paddle.linalg.matrix_exp; scaling-and-
    squaring via jax.scipy.linalg.expm)."""
    return jax.scipy.linalg.expm(x)


@def_op("ormqr")
def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by the FULL Q of a geqrf-style (householder)
    factorization (parity: paddle.linalg.ormqr). The m x n factor is
    zero-padded square so householder_product materializes all of Q —
    one extra MXU matmul vs LAPACK's implicit application."""
    m = x.shape[-2]
    k = tau.shape[-1]
    pad_cols = m - x.shape[-1]
    if pad_cols > 0:
        x = jnp.concatenate(
            [x, jnp.zeros(x.shape[:-1] + (pad_cols,), x.dtype)], axis=-1)
    if m - k > 0:
        tau = jnp.concatenate(
            [tau, jnp.zeros(tau.shape[:-1] + (m - k,), tau.dtype)],
            axis=-1)
    q = jax.lax.linalg.householder_product(x, tau)
    qm = jnp.swapaxes(q, -1, -2) if transpose else q
    return qm @ y if left else y @ qm


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, output_dtype="float16",
                            scale=1.0, act="identity", name=None):
    """fp8 x fp8 -> half GEMM (parity: paddle.linalg.fp8_fp8_half_gemm_fused,
    `phi/kernels/fusion/gpu/fp8_gemm`): on TPU the fp8 operands are
    MXU-multiplied with a half-precision accumulate-and-store — XLA fuses
    the scale/bias/activation epilogue like cublasLt does."""
    from ..core.dtype import convert_dtype
    out_dt = convert_dtype(output_dtype)

    def _f(a, b, *mb):
        bb = mb[0] if bias is not None else None
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jax.lax.dot_general(
            a, b, (((a.ndim - 1,), (b.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bb is not None:
            out = out + bb.astype(out.dtype)
        if act == "gelu":
            out = jax.nn.gelu(out)
        elif act == "relu":
            out = jax.nn.relu(out)
        return out.astype(out_dt)

    args = [x, y] + ([bias] if bias is not None else [])
    return apply_op("fp8_fp8_half_gemm_fused", _f, *args)
