"""Functional op surface (the phi-kernel-equivalent layer)."""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .dispatch import apply_op, def_op  # noqa: F401

from . import creation, math, manipulation, linalg, logic, search, random  # noqa: F401
from . import extras  # noqa: F401
from .extras import *  # noqa: F401,F403

__all__ = (
    creation.__all__ + math.__all__ + manipulation.__all__ + linalg.__all__
    + logic.__all__ + search.__all__ + random.__all__ + extras.__all__
)
