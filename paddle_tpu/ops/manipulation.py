"""Shape / layout manipulation ops.

Parity: reference `python/paddle/tensor/manipulation.py` and the stride/
concat/split/gather/scatter phi kernels. Gather/scatter map onto
jnp.take / Array.at[] which XLA lowers to TPU-friendly dynamic-slice /
scatter HLOs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dispatch import apply_op, def_op

__all__ = [
    "reshape", "transpose", "concat", "stack", "split", "chunk", "squeeze",
    "unsqueeze", "flatten", "flip", "roll", "tile", "expand", "expand_as",
    "broadcast_to", "broadcast_tensors", "broadcast_shape", "gather",
    "gather_nd", "scatter", "scatter_", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "masked_select",
    "masked_fill", "where", "take_along_axis", "put_along_axis", "slice",
    "strided_slice", "unbind", "unstack", "repeat_interleave", "rot90",
    "moveaxis", "swapaxes", "as_complex", "as_real", "cast", "crop",
    "tensordot", "unfold", "flatten_", "reshape_", "squeeze_", "unsqueeze_",
    "atleast_1d", "atleast_2d", "atleast_3d", "select_scatter",
    "view", "view_as", "unflatten", "dsplit", "hsplit", "vsplit",
    "row_stack", "column_stack", "hstack", "vstack", "dstack",
]


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    out = []
    for s in shape:
        out.append(int(s._data) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape(x, shape, name=None):
    sh = _static_shape(shape)
    return apply_op("reshape", lambda a: jnp.reshape(a, sh), x)


def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, _static_shape(shape))
    return x


view = reshape


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm=None, name=None):
    if perm is not None:
        perm = [int(p) for p in perm]
    return apply_op("transpose", lambda a: jnp.transpose(a, perm), x,
                    op_attrs={"perm": perm if perm is not None
                              else list(reversed(range(x.ndim)))})


def concat(x, axis=0, name=None):
    axis = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    return apply_op("concat", lambda xs: jnp.concatenate(xs, axis=axis),
                    list(x), op_attrs={"axis": axis})


def stack(x, axis=0, name=None):
    return apply_op("stack", lambda xs: jnp.stack(xs, axis=int(axis)),
                    list(x), op_attrs={"axis": int(axis)})


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sections = num_or_sections
        def _f(a):
            return tuple(jnp.split(a, sections, axis=axis))
    else:
        sizes = [int(s) for s in num_or_sections]
        # -1 placeholder support
        if any(s == -1 for s in sizes):
            known = builtins_sum(s for s in sizes if s != -1)
            sizes = [dim - known if s == -1 else s for s in sizes]
        offsets = np.cumsum(sizes)[:-1].tolist()
        def _f(a):
            return tuple(jnp.split(a, offsets, axis=axis))
    return list(apply_op("split", _f, x, op_attrs={"axis": axis}))


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def dsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=2)


def hsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=0)


def squeeze(x, axis=None, name=None):
    # normalized size-1 axes, shared by the kernel and the SPMD rule
    sq_axes = None if axis is None else \
        [int(ax) % x.ndim for ax in
         (axis if isinstance(axis, (list, tuple)) else [axis])
         if x.shape[int(ax) % x.ndim] == 1]

    def _f(a):
        if sq_axes is None:
            return jnp.squeeze(a)
        return jnp.squeeze(a, axis=tuple(sq_axes)) if sq_axes else a
    return apply_op("squeeze", _f, x,
                    op_attrs={"axis": sq_axes, "x_ndim": x.ndim})


def squeeze_(x, axis=None, name=None):
    x._data = squeeze(x.detach(), axis)._data
    return x


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._data) if isinstance(a, Tensor) else int(a) for a in axes]
    def _f(a):
        out = a
        for ax in axes:
            out = jnp.expand_dims(out, ax)
        return out
    return apply_op("unsqueeze", _f, x,
                    op_attrs={"axis": axes, "x_ndim": x.ndim})


def unsqueeze_(x, axis, name=None):
    x._data = unsqueeze(x.detach(), axis)._data
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    def _f(a):
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply_op("flatten", _f, x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    x._data = flatten(x.detach(), start_axis, stop_axis)._data
    return x


def unflatten(x, axis, shape, name=None):
    ax = axis % x.ndim
    sh = _static_shape(shape)
    def _f(a):
        return jnp.reshape(a, a.shape[:ax] + sh + a.shape[ax + 1:])
    return apply_op("unflatten", _f, x)


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(int(a) for a in axes)
    return apply_op("flip", lambda a: jnp.flip(a, axis=axes), x)


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply_op("tile", lambda a: jnp.tile(a, reps), x,
                    op_attrs={"repeat_times": list(reps), "x_ndim": x.ndim})


def expand(x, shape, name=None):
    sh = list(_static_shape(shape))
    def _f(a):
        # paddle allows -1 meaning "keep this dim"
        full = list(sh)
        offset = len(full) - a.ndim
        for i, s in enumerate(full):
            if s == -1 and i >= offset:
                full[i] = a.shape[i - offset]
        return jnp.broadcast_to(a, tuple(full))
    return apply_op("expand", _f, x,
                    op_attrs={"shape": list(sh), "x_ndim": x.ndim})


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    return apply_op("broadcast_tensors", lambda xs: tuple(jnp.broadcast_arrays(*xs)), list(inputs))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def cast(x, dtype):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    return apply_op("cast", lambda a: a.astype(d), x)


def gather(x, index, axis=0, name=None):
    axis = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    def _f(a, idx):
        return jnp.take(a, idx.reshape(-1) if idx.ndim > 1 else idx, axis=axis)
    return apply_op("gather", _f, x, index, op_attrs={"axis": axis})


@def_op("gather_nd")
def gather_nd(x, index, name=None):
    idx_depth = index.shape[-1]
    batch_shape = index.shape[:-1]
    flat_idx = index.reshape(-1, idx_depth)
    out = x[tuple(flat_idx[:, i] for i in range(idx_depth))]
    return out.reshape(batch_shape + x.shape[idx_depth:])


@def_op("scatter")
def scatter(x, index, updates, overwrite=True, name=None):
    idx = index.reshape(-1)
    if overwrite:
        return x.at[idx].set(updates)
    zeroed = x.at[idx].set(jnp.zeros_like(updates))
    return zeroed.at[idx].add(updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    x._data = scatter(x.detach(), index, updates, overwrite)._data
    return x


@def_op("scatter_nd")
def scatter_nd(index, updates, shape, name=None):
    out = jnp.zeros(tuple(int(s) for s in shape), updates.dtype)
    idx_depth = index.shape[-1]
    flat_idx = index.reshape(-1, idx_depth)
    flat_updates = updates.reshape((flat_idx.shape[0],) + updates.shape[index.ndim - 1:])
    return out.at[tuple(flat_idx[:, i] for i in range(idx_depth))].add(flat_updates)


@def_op("scatter_nd_add")
def scatter_nd_add(x, index, updates, name=None):
    idx_depth = index.shape[-1]
    flat_idx = index.reshape(-1, idx_depth)
    flat_updates = updates.reshape((flat_idx.shape[0],) + updates.shape[index.ndim - 1:])
    return x.at[tuple(flat_idx[:, i] for i in range(idx_depth))].add(flat_updates)


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select", lambda a, i: jnp.take(a, i, axis=int(axis)), x, index)


@def_op("index_sample")
def index_sample(x, index):
    rows = jnp.arange(x.shape[0])[:, None]
    return x[rows, index]


@def_op("index_add")
def index_add(x, index, axis, value, name=None):
    ax = int(axis) % x.ndim
    moved = jnp.moveaxis(x, ax, 0)
    vmoved = jnp.moveaxis(value, ax, 0)
    out = moved.at[index].add(vmoved)
    return jnp.moveaxis(out, 0, ax)


@def_op("index_put")
def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


@def_op("masked_select")
def masked_select(x, mask, name=None):
    # dynamic-shape op: eager only (jit requires static sizes)
    return x[mask]


@def_op("masked_fill")
def masked_fill(x, mask, value, name=None):
    v = value if not hasattr(value, "astype") else value.astype(x.dtype)
    return jnp.where(mask, v, x)


@def_op("where")
def _where3(condition, x, y, name=None):
    return jnp.where(condition, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero
        return nonzero(condition, as_tuple=True)
    return _where3(condition, x, y)


@def_op("take_along_axis")
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = indices
    if broadcast:
        shape = list(np.broadcast_shapes(tuple(arr.shape[:axis]) + (1,) + tuple(arr.shape[axis + 1:]),
                                         idx.shape))
        shape[axis] = idx.shape[axis]
        idx = jnp.broadcast_to(idx, shape)
    return jnp.take_along_axis(arr, idx, axis=axis)


@def_op("put_along_axis")
def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    vals = values if hasattr(values, "shape") else jnp.full(indices.shape, values, arr.dtype)
    vals = jnp.broadcast_to(vals, indices.shape).astype(arr.dtype)
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, vals, axis=axis, inplace=False)
    ax = axis % arr.ndim
    idx_grid = jnp.indices(indices.shape, sparse=False)
    full_idx = tuple(idx_grid[i] if i != ax else indices for i in range(arr.ndim))
    if reduce in ("add", "sum"):
        return arr.at[full_idx].add(vals)
    if reduce in ("mul", "multiply"):
        return arr.at[full_idx].multiply(vals)
    if reduce == "amax":
        return arr.at[full_idx].max(vals)
    if reduce == "amin":
        return arr.at[full_idx].min(vals)
    raise ValueError(f"unsupported reduce: {reduce}")


def slice(input, axes, starts, ends, name=None):
    starts = [int(s._data) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e._data) if isinstance(e, Tensor) else int(e) for e in ends]
    def _f(a):
        idx = [jnp.s_[:]] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[int(ax)] = jnp.s_[st:en]
        return a[tuple(idx)]
    return apply_op("slice", _f, input,
                    op_attrs={"axes": [int(a) for a in axes]})


def strided_slice(x, axes, starts, ends, strides, name=None):
    def _f(a):
        idx = [jnp.s_[:]] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[int(ax)] = jnp.s_[int(st):int(en):int(sd)]
        return a[tuple(idx)]
    return apply_op("strided_slice", _f, x,
                    op_attrs={"axes": [int(a) for a in axes]})


def unbind(input, axis=0, name=None):
    n = input.shape[axis]
    def _f(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))
    return list(apply_op("unbind", _f, input, op_attrs={"axis": axis}))


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = np.asarray(repeats._data)
        total = int(repeats.sum())
        return apply_op("repeat_interleave",
                        lambda a: jnp.repeat(a, jnp.asarray(repeats), axis=axis,
                                             total_repeat_length=total), x)
    return apply_op("repeat_interleave", lambda a: jnp.repeat(a, int(repeats), axis=axis), x)


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, int(axis0), int(axis1)), x)


@def_op("as_complex")
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1])


@def_op("as_real")
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@def_op("crop")
def crop(x, shape=None, offsets=None, name=None):
    offs = offsets if offsets is not None else [0] * x.ndim
    sh = [x.shape[i] if (shape is None or shape[i] == -1) else int(shape[i]) for i in range(x.ndim)]
    idx = tuple(jnp.s_[int(o):int(o) + int(s)] for o, s in zip(offs, sh))
    return x[idx]


@def_op("tensordot")
def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)) and len(axes) == 2:
        axes = (tuple(axes[0]) if isinstance(axes[0], (list, tuple)) else (axes[0],),
                tuple(axes[1]) if isinstance(axes[1], (list, tuple)) else (axes[1],))
    return jnp.tensordot(x, y, axes=axes)


@def_op("unfold")
def unfold(x, axis, size, step, name=None):
    ax = axis % x.ndim
    n = (x.shape[ax] - size) // step + 1
    starts = jnp.arange(n) * step
    def take_window(s):
        return jax.lax.dynamic_slice_in_dim(x, s, size, axis=ax)
    out = jax.vmap(take_window)(starts)  # (n, ...) window at axis ax
    out = jnp.moveaxis(out, 0, ax)       # windows indexed at ax
    return jnp.moveaxis(out, ax + 1, x.ndim)  # window content to last dim


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


@def_op("select_scatter")
def select_scatter(x, values, axis, index, name=None):
    idx = [jnp.s_[:]] * x.ndim
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(values)


def hstack(x, name=None):
    return apply_op("hstack", lambda xs: jnp.hstack(xs), list(x))


def vstack(x, name=None):
    return apply_op("vstack", lambda xs: jnp.vstack(xs), list(x))


def dstack(x, name=None):
    return apply_op("dstack", lambda xs: jnp.dstack(xs), list(x))


row_stack = vstack


def column_stack(x, name=None):
    return apply_op("column_stack", lambda xs: jnp.column_stack(xs), list(x))
