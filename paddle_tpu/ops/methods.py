"""Attach the op surface onto Tensor as methods/operators.

Parity: the reference monkey-patches ~400 functions onto paddle.Tensor
(`python/paddle/tensor/__init__.py` tensor_method_func list +
`paddle/fluid/pybind/eager_math_op_patch.cc` operator overloads).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import creation, extras, linalg, logic, manipulation, math, search
from .dispatch import apply_op


def _unwrap_index(item):
    """Pass-through: Tensors inside the index pytree are handled by apply_op."""
    return item


def _getitem(self, item):
    return apply_op("getitem", lambda a, idx: a[idx if not isinstance(idx, list) else tuple(idx)],
                    self, item)


def _alias(t):
    """Snapshot a Tensor's current value+autograd identity. In-place ops must
    record the op against this alias, not the mutated tensor itself —
    otherwise the new grad node lists its own output as an input (a cycle),
    the same hazard the reference guards with inplace version counters."""
    a = Tensor(t._data, stop_gradient=t.stop_gradient, name=t.name)
    a._grad_node = t._grad_node
    a._grad_out_idx = t._grad_out_idx
    return a


def _rebind(self, out):
    self._data = out._data
    self._grad_node = out._grad_node
    self._grad_out_idx = out._grad_out_idx
    self.stop_gradient = out.stop_gradient
    return self


def _setitem(self, item, value):
    out = apply_op(
        "set_value",
        lambda a, idx, v: a.at[idx if not isinstance(idx, list) else tuple(idx)].set(
            v.astype(a.dtype) if hasattr(v, "astype") else v),
        _alias(self), item, value)
    return _rebind(self, out)


_BINARY_DUNDERS = {
    "__add__": math.add,
    "__radd__": lambda x, y: math.add(y, x) if isinstance(y, Tensor) else apply_op("add", lambda a: jnp.add(y, a), x),
    "__sub__": math.subtract,
    "__rsub__": lambda x, y: apply_op("rsub", lambda a: jnp.subtract(y._data if isinstance(y, Tensor) else y, a), x),
    "__mul__": math.multiply,
    "__rmul__": lambda x, y: apply_op("rmul", lambda a: jnp.multiply(y._data if isinstance(y, Tensor) else y, a), x),
    "__truediv__": math.divide,
    "__rtruediv__": lambda x, y: apply_op("rdiv", lambda a: jnp.true_divide(y._data if isinstance(y, Tensor) else y, a), x),
    "__floordiv__": math.floor_divide,
    "__rfloordiv__": lambda x, y: apply_op("rfloordiv", lambda a: jnp.floor_divide(y._data if isinstance(y, Tensor) else y, a), x),
    "__mod__": math.mod,
    "__rmod__": lambda x, y: apply_op("rmod", lambda a: jnp.mod(y._data if isinstance(y, Tensor) else y, a), x),
    "__pow__": math.pow,
    "__rpow__": lambda x, y: apply_op("rpow", lambda a: jnp.power(y._data if isinstance(y, Tensor) else y, a), x),
    "__matmul__": linalg.matmul,
    "__rmatmul__": lambda x, y: apply_op("rmatmul", lambda a: jnp.matmul(y._data if isinstance(y, Tensor) else y, a), x),
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
    "__lshift__": logic.bitwise_left_shift,
    "__rshift__": logic.bitwise_right_shift,
}


def _neg(self):
    return math.neg(self)


def _invert(self):
    return logic.bitwise_not(self) if not jnp.issubdtype(self.dtype, jnp.bool_) else logic.logical_not(self)


def _abs(self):
    return math.abs(self)


def _inplace(op):
    def fn(self, other):
        return _rebind(self, op(_alias(self), other))
    return fn


# Named methods lifted straight from the functional modules.
_METHOD_SOURCES = [math, manipulation, linalg, logic, search, extras]
_SKIP = {"where",
         # extras whose first arg is not a tensor (creation/list-first):
         # attaching them as methods would misbind `self`
         "gaussian", "fill_constant", "create_tensor", "create_global_var",
         "block_diag", "cartesian_prod", "add_n", "multiplex"}


def patch_tensor_methods():
    _bind_inplace_random()
    for name, fn in _BINARY_DUNDERS.items():
        setattr(Tensor, name, fn)
    Tensor.__neg__ = _neg
    Tensor.__invert__ = _invert
    Tensor.__abs__ = _abs
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.__iadd__ = _inplace(math.add)
    Tensor.__isub__ = _inplace(math.subtract)
    Tensor.__imul__ = _inplace(math.multiply)
    Tensor.__itruediv__ = _inplace(math.divide)

    for mod in _METHOD_SOURCES:
        for name in mod.__all__:
            if name in _SKIP or hasattr(Tensor, name):
                continue
            fn = getattr(mod, name)
            if callable(fn):
                setattr(Tensor, name, fn)

    # aliases / special-arg-order methods
    Tensor.add_ = _inplace(math.add)
    Tensor.subtract_ = _inplace(math.subtract)
    Tensor.multiply_ = _inplace(math.multiply)
    Tensor.scale_ = _inplace(math.scale)
    Tensor.clip_ = _inplace_unary(math.clip)
    Tensor.mod_ = _inplace(math.mod)
    Tensor.where = lambda self, x, y=None: manipulation.where(self, x, y) \
        if jnp.issubdtype(self.dtype, jnp.bool_) else manipulation.where(self > 0, x, y)
    Tensor.tril_ = _inplace_unary(creation.tril)

    # ---- generated in-place (`op_`) variants (reference tensor API tail):
    # every base op gains an op_ that rebinds the tensor through the tape
    # (the reference's inplace kernels; here a rebind after the pure op)
    unary_inplace = [
        "abs", "acos", "asin", "atan", "ceil", "cos", "cosh", "digamma",
        "erf", "erfinv", "exp", "expm1", "floor", "frac", "lgamma", "log",
        "log10", "log1p", "log2", "logit", "neg", "reciprocal", "round",
        "rsqrt", "sigmoid", "sin", "sinh", "sqrt", "square", "tan", "tanh",
        "trunc", "i0", "gammaln", "nan_to_num", "cast", "cumsum", "cumprod",
        "polygamma", "multigammaln", "uniform", "normal", "bernoulli",
        "bitwise_not", "logical_not", "sinc", "renorm", "t", "transpose",
        "index_add", "index_fill", "index_put", "masked_fill",
        "masked_scatter", "put_along_axis", "fill_diagonal_tensor", "addmm",
        "lerp",  # 3-arg: needs the *args wrapper
    ]
    binary_inplace = [
        "divide", "floor_divide", "remainder", "pow", "copysign", "hypot",
        "gcd", "lcm", "ldexp", "bitwise_and", "bitwise_or",
        "bitwise_xor", "bitwise_left_shift", "bitwise_right_shift",
        "logical_and", "logical_or", "logical_xor", "equal", "not_equal",
        "greater_equal", "greater_than", "less_equal", "less_than",
        "maximum", "minimum", "fmax", "fmin", "gammainc", "gammaincc",
    ]
    for base in unary_inplace:
        fn = getattr(Tensor, base, None)
        if fn is not None and not hasattr(Tensor, base + "_"):
            setattr(Tensor, base + "_", _inplace_unary(fn))
    for base in binary_inplace:
        fn = getattr(Tensor, base, None)
        if fn is not None and not hasattr(Tensor, base + "_"):
            setattr(Tensor, base + "_", _inplace(fn))

    def _where_(self, x, y=None):
        return _rebind(self, Tensor.where(_alias(self), x, y))

    def _gaussian_(self, mean=0.0, std=1.0):
        from .extras import gaussian
        return _rebind(self, gaussian(self.shape, mean, std,
                                      dtype=str(self.dtype)))

    def _log_normal_(self, mean=1.0, std=2.0):
        from .extras import gaussian
        g = gaussian(self.shape, mean, std, dtype=str(self.dtype))
        return _rebind(self, apply_op("exp", jnp.exp, g))

    def _bernoulli_(self, p=0.5):
        from ..framework.random import rng_key
        key = rng_key()
        return _rebind(self, apply_op(
            "bernoulli_",
            lambda a: jax.random.bernoulli(key, p, a.shape).astype(a.dtype),
            _alias(self)))

    Tensor.where_ = _where_
    Tensor.gaussian_ = _gaussian_
    Tensor.log_normal_ = _log_normal_
    Tensor.bernoulli_ = _bernoulli_
    Tensor.triu_ = _inplace_unary(creation.triu)
    Tensor.zero_ = Tensor.zero_
    Tensor.unsqueeze_ = manipulation.unsqueeze_
    Tensor.squeeze_ = manipulation.squeeze_
    Tensor.reshape_ = manipulation.reshape_
    Tensor.flatten_ = manipulation.flatten_


def _inplace_unary(op):
    def fn(self, *args, **kwargs):
        return _rebind(self, op(_alias(self), *args, **kwargs))
    return fn


def _bind_inplace_random():
    from ..core.tensor import Tensor
    from . import random as _r
    Tensor.uniform_ = _r.uniform_
    Tensor.normal_ = _r.normal_
    Tensor.exponential_ = _r.exponential_
