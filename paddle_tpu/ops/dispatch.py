"""Op dispatch: turn a jnp-level function into an autograd-tracked Tensor op.

Role parity with the reference's generated op pipeline
(`/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py`:
per-op `xxx_ad_func` = AMP cast -> forward kernel -> GradNode creation).
Here one generic wrapper replaces ~300k lines of generated C++: the forward
is any jnp/lax composition, and the backward comes from `jax.vjp` at call
time — every op gets a correct, XLA-fused gradient for free, which is the
single-source-of-truth property the reference gets from ops.yaml codegen.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.autograd import GradNode
from ..core.tensor import Tensor

__all__ = ["apply_op", "def_op"]


def _is_tensor(x):
    return isinstance(x, Tensor)


_profiler_mod = None
_nan_inf_mod = None
_spmd_prop = None
# jit.loop_grad external-tensor capture (active only while a converted
# loop probes its body / traces its scan lowering); one None-check per op
_loop_capture = None


def apply_op(name: str, fn: Callable, *args, **kwargs):
    """Profiler-aware entry: when a Profiler is recording, every op emits a
    host RecordEvent span (parity: RecordEvent emission in each generated
    ad_func, `phi/api/profiler/event_tracing.h:32`). Costs one attribute
    check when profiling is off."""
    global _profiler_mod
    if _profiler_mod is None:
        from .. import profiler as _p
        _profiler_mod = _p
    if _profiler_mod._tracer.enabled:
        ev = _profiler_mod.RecordEvent(
            name, _profiler_mod.TracerEventType.Operator)
        ev.begin()
        try:
            return _apply_op(name, fn, *args, **kwargs)
        finally:
            ev.end()
    return _apply_op(name, fn, *args, **kwargs)


def _apply_op(name: str, fn: Callable, *args, **kwargs):
    """Execute `fn` (a function over jax arrays) on Tensor/array args.

    - Tensors anywhere in (args, kwargs) — including inside lists/tuples/dicts
      (e.g. `concat([t1, t2])`) — are treated as differentiable inputs.
    - If grad is enabled and any input Tensor requires grad, the op is
      recorded on the tape via `jax.vjp`.
    - Outputs (array or pytree of arrays) are wrapped back into Tensors.
    - `op_attrs=` is a reserved side-channel: a dict of static attributes
      (axis, perm, ...) that is NOT forwarded to `fn` (call sites close
      attrs into their lambdas) but IS visible to the SPMD propagation
      hook — the role the reference's op attrs play for InferSpmd
      (`dist_api_gen.py:49-110`). VERDICT r3 weak #3.
    """
    op_attrs = kwargs.pop("op_attrs", None)
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    tensors = [leaves[i] for i in t_pos]
    arrays = [t._data for t in tensors]

    # AMP hook (parity: AMP autocast step in the reference's generated
    # ad_func, eager_gen.py:1910): cast float inputs per allow/deny lists.
    from ..amp.auto_cast import amp_dtype_for_op
    amp_dtype = amp_dtype_for_op(name)
    if amp_dtype is not None:
        arrays = [a.astype(amp_dtype)
                  if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != amp_dtype
                  else a for a in arrays]

    def closed(*arrs):
        new_leaves = list(leaves)
        for i, a in zip(t_pos, arrs):
            new_leaves[i] = a
        a2, k2 = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return fn(*a2, **k2)

    need_grad = autograd.is_grad_enabled() and any(
        not t.stop_gradient for t in tensors)

    if need_grad:
        out, vjp_fn = jax.vjp(closed, *arrays)
    else:
        out = closed(*arrays)

    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)

    # NaN/Inf hook (cached module ref like _profiler_mod: this runs on
    # EVERY op). maybe_check raises FloatingPointError carrying the op
    # name and any active `nan_inf.poison_scope` label — the serving
    # supervisor classifies that as deterministic poison (quarantine the
    # attributed request, never retry).
    global _nan_inf_mod
    if _nan_inf_mod is None:
        from ..utils import nan_inf as _ni
        _nan_inf_mod = _ni
    if _nan_inf_mod.check_nan_inf_enabled():
        _nan_inf_mod.maybe_check(name, out_leaves)

    from ..amp import debugging as _amp_dbg
    if _amp_dbg._is_collecting():
        _amp_dbg._record(name, out_leaves)

    out_tensors = []
    node = None
    if need_grad:
        avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_leaves]
        node = GradNode(name, vjp_fn, tensors, avals, out_treedef,
                        fwd_closed=closed)
    for idx, o in enumerate(out_leaves):
        differentiable = need_grad and jnp.issubdtype(o.dtype, jnp.inexact)
        t = Tensor(o, stop_gradient=not differentiable)
        if differentiable:
            t._grad_node = node
            t._grad_out_idx = idx
        out_tensors.append(t)
    if _loop_capture is not None:
        _loop_capture.observe(tensors, out_tensors)
    # SPMD rule propagation hook (parity: InferSpmd step of the generated
    # dist branch, dist_api_gen.py:49-110) — active only inside a
    # spmd_propagation(mesh) scope; one dict lookup otherwise.
    global _spmd_prop
    if _spmd_prop is None:
        from ..distributed.auto_parallel import propagation as _sp
        _spmd_prop = _sp
    if _spmd_prop._STATE["mesh"] is not None:
        _spmd_prop.maybe_constrain(
            name, tensors, out_tensors,
            {**kwargs, **op_attrs} if op_attrs else kwargs)
    return jax.tree_util.tree_unflatten(out_treedef, out_tensors)


def def_op(name: str):
    """Decorator form: define a Tensor-level op from a jnp-level function.

    >>> @def_op("tanh")
    ... def tanh(x):
    ...     return jnp.tanh(x)
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return apply_op(name, fn, *args, **kwargs)
        wrapper.raw = fn  # array-level implementation, for jit-internal use
        return wrapper
    return deco
