"""Elementwise + reduction math ops.

Parity: reference `python/paddle/tensor/math.py` (~6k LoC of API) and the
corresponding phi kernels (`paddle/phi/kernels/*_kernel.h`). Each op is a
jnp/lax composition; gradients come from jax.vjp via the dispatch layer, so
forward+grad parity with the reference's (kernel, grad-kernel) pairs is one
definition here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dispatch import apply_op, def_op

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "sqrt", "rsqrt", "exp", "expm1",
    "log", "log2", "log10", "log1p", "abs", "neg", "sign", "sgn", "floor",
    "ceil", "round", "trunc", "frac", "sin", "cos", "tan", "asin", "acos",
    "atan", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "atan2",
    "reciprocal", "square", "clip", "maximum", "minimum", "fmax", "fmin",
    "sum", "mean", "max", "min", "amax", "amin", "prod", "std", "var",
    "median", "nanmedian", "cumsum", "cumprod", "cummax", "cummin",
    "logsumexp", "logcumsumexp", "isnan", "isinf", "isfinite", "nan_to_num",
    "erf", "erfinv", "lgamma", "digamma", "gammaln", "multigammaln",
    "inner", "outer", "kron", "trace", "all", "any", "count_nonzero",
    "nansum", "nanmean", "angle", "conj", "real", "imag", "lerp",
    "rad2deg", "deg2rad", "gcd", "lcm", "diff", "heaviside", "hypot",
    "ldexp", "logaddexp", "logit", "scale", "stanh", "addmm", "increment",
    "log_normalize", "renorm", "trapezoid", "cumulative_trapezoid",
    "vander", "i0", "i0e", "i1", "i1e", "polygamma", "combinations",
    "signbit", "copysign", "nextafter", "frexp", "sinc", "take",
    "igamma", "igammac",
]

# ----------------------------------------------------------------- binary


def _binary(op_name, fn):
    # note: the paddle-API `name=None` kwarg must not shadow the op name
    def op(x, y, name=None):
        return apply_op(op_name, fn, _as_t(x), _as_t(y))
    op.__name__ = op_name
    op.raw = fn
    return op


def _as_t(x):
    return x if isinstance(x, Tensor) else x  # python scalars pass through


add = _binary("add", lambda x, y: jnp.add(x, y))
subtract = _binary("subtract", lambda x, y: jnp.subtract(x, y))
multiply = _binary("multiply", lambda x, y: jnp.multiply(x, y))
divide = _binary("divide", lambda x, y: jnp.true_divide(x, y))
floor_divide = _binary("floor_divide", lambda x, y: jnp.floor_divide(x, y))
mod = _binary("mod", lambda x, y: jnp.mod(x, y))
remainder = mod
pow = _binary("pow", lambda x, y: jnp.power(x, y))
float_power = _binary("float_power", lambda x, y: jnp.float_power(x, y))
maximum = _binary("maximum", lambda x, y: jnp.maximum(x, y))
minimum = _binary("minimum", lambda x, y: jnp.minimum(x, y))
fmax = _binary("fmax", lambda x, y: jnp.fmax(x, y))
fmin = _binary("fmin", lambda x, y: jnp.fmin(x, y))
atan2 = _binary("atan2", lambda x, y: jnp.arctan2(x, y))
gcd = _binary("gcd", lambda x, y: jnp.gcd(x, y))
lcm = _binary("lcm", lambda x, y: jnp.lcm(x, y))
heaviside = _binary("heaviside", lambda x, y: jnp.heaviside(x, y))
hypot = _binary("hypot", lambda x, y: jnp.hypot(x, y))
ldexp = _binary("ldexp", lambda x, y: jnp.ldexp(x, y))
logaddexp = _binary("logaddexp", lambda x, y: jnp.logaddexp(x, y))
copysign = _binary("copysign", lambda x, y: jnp.copysign(x, y))
nextafter = _binary("nextafter", lambda x, y: jnp.nextafter(x, y))

# ------------------------------------------------------------------ unary


def _unary(op_name, fn):
    def op(x, name=None):
        return apply_op(op_name, fn, x)
    op.__name__ = op_name
    op.raw = fn
    return op


sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
sign = _unary("sign", jnp.sign)
sgn = sign
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
reciprocal = _unary("reciprocal", jnp.reciprocal)
square = _unary("square", jnp.square)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
gammaln = lgamma
digamma = _unary("digamma", jax.scipy.special.digamma)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)
i0 = _unary("i0", jax.scipy.special.i0)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)
signbit = _unary("signbit", jnp.signbit)
sinc = _unary("sinc", jnp.sinc)


@def_op("logit")
def logit(x, eps=None, name=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@def_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@def_op("clip")
def clip(x, min=None, max=None, name=None):
    lo = min.astype(x.dtype) if hasattr(min, "astype") else min
    hi = max.astype(x.dtype) if hasattr(max, "astype") else max
    return jnp.clip(x, lo, hi)


@def_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@def_op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = jnp.asarray(scale, x.dtype) if not isinstance(scale, (int, float)) else scale
    if bias_after_scale:
        out = x * s + bias
    else:
        out = (x + bias) * s
    return out.astype(x.dtype) if hasattr(out, "astype") else out


def increment(x, value=1.0, name=None):
    x._data = x._data + jnp.asarray(value, x.dtype)
    return x


@def_op("multigammaln")
def multigammaln(x, p, name=None):
    return jax.scipy.special.multigammaln(x, p)


@def_op("polygamma")
def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, x)


# -------------------------------------------------------------- reductions


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduction(op_name, fn, bool_out=False):
    def op(x, axis=None, keepdim=False, name=None):
        ax = _norm_axis(axis)
        return apply_op(op_name,
                        lambda a: fn(a, axis=ax, keepdims=keepdim), x,
                        op_attrs={"axis": ax, "keepdim": keepdim})
    op.__name__ = op_name
    return op


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _sum(a):
        out = jnp.sum(a, axis=ax, keepdims=keepdim, dtype=d)
        if d is None and jnp.issubdtype(a.dtype, jnp.bool_):
            out = out.astype(jnp.int64)
        return out
    return apply_op("sum", _sum, x, op_attrs={"axis": ax, "keepdim": keepdim})


mean = _reduction("mean", jnp.mean)
prod = _reduction("prod", jnp.prod)
amax = _reduction("amax", jnp.max)
amin = _reduction("amin", jnp.min)
nansum = _reduction("nansum", jnp.nansum)
nanmean = _reduction("nanmean", jnp.nanmean)
all = _reduction("all", jnp.all)
any = _reduction("any", jnp.any)


def max(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("max", lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x,
                    op_attrs={"axis": ax, "keepdim": keepdim})


def min(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("min", lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x,
                    op_attrs={"axis": ax, "keepdim": keepdim})


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op("std", lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op("var", lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)
    return apply_op("median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("nanmedian", lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("count_nonzero",
                    lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(jnp.int64), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("logsumexp",
                    lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim),
                    x, op_attrs={"axis": ax, "keepdim": keepdim})


@def_op("logcumsumexp")
def logcumsumexp(x, axis=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    m = jax.lax.cummax(x, axis=axis)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    s = jnp.cumsum(jnp.exp(x - m_safe), axis=axis)
    # correct for running max changes: recompute with stable two-pass trick
    gm = jnp.max(x, axis=axis, keepdims=True)
    gm_safe = jnp.where(jnp.isfinite(gm), gm, 0.0)
    return jnp.log(jnp.cumsum(jnp.exp(x - gm_safe), axis=axis)) + gm_safe


def cumsum(x, axis=None, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _f(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)
    return apply_op("cumsum", _f, x,
                    op_attrs={"axis": None if axis is None else int(axis)})


def cumprod(x, dim=None, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _f(a):
        if dim is None:
            a = a.reshape(-1)
            return jnp.cumprod(a, dtype=d)
        return jnp.cumprod(a, axis=int(dim), dtype=d)
    return apply_op("cumprod", _f, x,
                    op_attrs={"axis": None if dim is None else int(dim)})


def cummax(x, axis=None, dtype="int64", name=None):
    def _f(a):
        ax = 0 if axis is None else int(axis)
        aa = a.reshape(-1) if axis is None else a
        vals = jax.lax.cummax(aa, axis=ax)
        n = aa.shape[ax]
        eq = aa == vals
        idx = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(aa.ndim)])
        idx = jnp.broadcast_to(idx, aa.shape)
        indices = jax.lax.cummax(jnp.where(eq, idx, -1), axis=ax)
        return vals, indices.astype(jnp.int64)
    return apply_op("cummax", _f, x,
                    op_attrs={"axis": None if axis is None else int(axis)})


def cummin(x, axis=None, dtype="int64", name=None):
    def _f(a):
        ax = 0 if axis is None else int(axis)
        aa = a.reshape(-1) if axis is None else a
        vals = jax.lax.cummin(aa, axis=ax)
        n = aa.shape[ax]
        eq = aa == vals
        idx = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(aa.ndim)])
        idx = jnp.broadcast_to(idx, aa.shape)
        indices = jax.lax.cummax(jnp.where(eq, idx, -1), axis=ax)
        return vals, indices.astype(jnp.int64)
    return apply_op("cummin", _f, x,
                    op_attrs={"axis": None if axis is None else int(axis)})


# ------------------------------------------------------------ linalg-lite


@def_op("inner")
def inner(x, y, name=None):
    return jnp.inner(x, y)


@def_op("outer")
def outer(x, y, name=None):
    return jnp.outer(x, y)


@def_op("kron")
def kron(x, y, name=None):
    return jnp.kron(x, y)


@def_op("trace")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@def_op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * (x @ y)


@def_op("lerp")
def lerp(x, y, weight, name=None):
    return x + weight * (y - x)


@def_op("diff")
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@def_op("trapezoid")
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is None and dx is None:
        dx = 1.0
    return jnp.trapezoid(y, x=x, dx=dx if dx is not None else 1.0, axis=axis)


@def_op("cumulative_trapezoid")
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    n = y.shape[axis]
    y0 = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, n, axis=axis)
    avg = (y0 + y1) / 2.0
    if x is not None:
        x0 = jax.lax.slice_in_dim(x, 0, n - 1, axis=axis)
        x1 = jax.lax.slice_in_dim(x, 1, n, axis=axis)
        avg = avg * (x1 - x0)
    else:
        avg = avg * (1.0 if dx is None else dx)
    return jnp.cumsum(avg, axis=axis)


@def_op("vander")
def vander(x, n=None, increasing=False, name=None):
    return jnp.vander(x, N=n, increasing=increasing)


@def_op("renorm")
def renorm(x, p, axis, max_norm, name=None):
    dims = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


@def_op("log_normalize")
def log_normalize(x, axis=-1, name=None):
    return x - jax.scipy.special.logsumexp(x, axis=axis, keepdims=True)


@def_op("frexp")
def frexp(x, name=None):
    m, e = jnp.frexp(x)
    return m, e


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    n = x.shape[0]
    gen = itertools.combinations_with_replacement if with_replacement else itertools.combinations
    idx = np.asarray(list(gen(range(n), r)), dtype=np.int64)
    if idx.size == 0:
        idx = idx.reshape(0, r)
    return apply_op("combinations", lambda a: a[idx], x)


@def_op("take")
def take(x, index, mode="raise", name=None):
    return jnp.take(x.reshape(-1), index.reshape(-1), mode="clip" if mode != "wrap" else "wrap").reshape(index.shape)


def igamma(x, y, name=None):
    """Regularized UPPER incomplete gamma Q(x, y) — Paddle's igamma is
    igamc (phi IgammaFunctor, impl/gammaincc_kernel_impl.h:112), i.e. the
    complement of scipy's gammainc. Alias of gammaincc (ops/extras.py)."""
    from .extras import gammaincc
    return gammaincc(x, y)


def igammac(x, y, name=None):
    """Regularized LOWER incomplete gamma P(x, y) (Paddle igammac ==
    gammainc). Alias of gammainc (ops/extras.py)."""
    from .extras import gammainc
    return gammainc(x, y)
