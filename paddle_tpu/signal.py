"""paddle.signal — frame / overlap_add / stft / istft.

Parity: reference `python/paddle/signal.py` (stft:272, istft:449, built
on frame/overlap_add ops `paddle/phi/kernels/frame_kernel.h`,
`overlap_add_kernel.h`).

TPU-native: framing is a strided gather and the FFT goes through XLA's
native FFT lowering; everything is static-shaped, differentiable, and
jit-friendly. The audio feature stack (audio.Spectrogram etc.) layers on
the same primitives.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor
from .ops.dispatch import apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along `axis`.

    axis=-1: (..., seq) -> (..., frame_length, num_frames);
    axis=0:  (seq, ...) -> (num_frames, frame_length, ...).
    """
    def _f(a):
        if axis in (-1, a.ndim - 1):
            n = a.shape[-1]
            num = 1 + (n - frame_length) // hop_length
            idx = (jnp.arange(frame_length)[:, None]
                   + hop_length * jnp.arange(num)[None, :])
            return a[..., idx]
        if axis == 0:
            n = a.shape[0]
            num = 1 + (n - frame_length) // hop_length
            idx = (hop_length * jnp.arange(num)[:, None]
                   + jnp.arange(frame_length)[None, :])
            return a[idx]
        raise ValueError("frame supports axis 0 or -1")
    return apply_op("frame", _f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: sum overlapping frames.

    axis=-1: (..., frame_length, num_frames) -> (..., seq)."""
    def _f(a):
        if axis in (-1, a.ndim - 1):
            fl, num = a.shape[-2], a.shape[-1]
            out_len = (num - 1) * hop_length + fl
            seg = jnp.zeros(a.shape[:-2] + (out_len,), a.dtype)
            pos = (hop_length * jnp.arange(num)[None, :]
                   + jnp.arange(fl)[:, None])       # (fl, num)
            return seg.at[..., pos].add(a)
        if axis == 0:
            num, fl = a.shape[0], a.shape[1]
            out_len = (num - 1) * hop_length + fl
            seg = jnp.zeros((out_len,) + a.shape[2:], a.dtype)
            pos = (hop_length * jnp.arange(num)[:, None]
                   + jnp.arange(fl)[None, :])       # (num, fl)
            return seg.at[pos].add(a)
        raise ValueError("overlap_add supports axis 0 or -1")
    return apply_op("overlap_add", _f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform.

    x: (N, T) or (T,) real (or complex with onesided=False).
    Returns (N, n_fft//2+1 or n_fft, num_frames) complex.
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = _arr(window) if window is not None else jnp.ones(win_length)
    if win_length < n_fft:  # center-pad the window to n_fft
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def _f(a, w):
        is_complex = jnp.iscomplexobj(a)
        if center:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(n_fft // 2,) * 2],
                        mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[:, None]
               + hop_length * jnp.arange(num)[None, :])
        frames = a[..., idx] * w[:, None]           # (..., n_fft, num)
        if onesided and not is_complex:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-2)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    return apply_op("stft", _f, x, Tensor(win))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with overlap-add and window-envelope normalization.

    x: (N, freq, num_frames) complex. Round-trips stft for windows
    satisfying the NOLA constraint.
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = _arr(window) if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def _f(spec, w):
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, n=n_fft, axis=-2)
            if not return_complex:
                frames = frames.real
        frames = frames * w[:, None]
        num = frames.shape[-1]
        out_len = (num - 1) * hop_length + n_fft
        pos = (hop_length * jnp.arange(num)[None, :]
               + jnp.arange(n_fft)[:, None])
        sig = jnp.zeros(frames.shape[:-2] + (out_len,),
                        frames.dtype).at[..., pos].add(frames)
        env = jnp.zeros(out_len).at[pos.reshape(-1)].add(
            jnp.tile((w ** 2)[:, None], (1, num)).reshape(-1))
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            sig = sig[..., n_fft // 2:out_len - n_fft // 2]
        if length is not None:
            if sig.shape[-1] < length:  # frames don't cover the tail
                sig = jnp.pad(sig, [(0, 0)] * (sig.ndim - 1)
                              + [(0, length - sig.shape[-1])])
            sig = sig[..., :length]
        return sig

    return apply_op("istft", _f, x, Tensor(win))
