"""Collective-traffic accounting per compiled program (ISSUE 12).

`profiler/cost.py` (ISSUE 11) made FLOPs/HBM-bytes claims derivable from
the compiled program; this module does the same for COMMUNICATION. It
walks the post-SPMD-partitioning HLO text of a compiled jit program
(`compiled.as_text()` — the same `lowered.compile()` access path
`cost.py` uses) for the five collective families XLA emits

    all-reduce, all-gather, reduce-scatter, all-to-all,
    collective-permute  (async `-start` forms counted, `-done` skipped)

and turns operand shapes + replica groups into per-op records and a
per-MESH-AXIS attribution of op counts and payload bytes — "how many
bytes does this step move over which axis" becomes a dict, not an HLO
reading session. Parity: the reference pairs its executors with a comm
cost model (`paddle/fluid/distributed/fleet_executor/` +
`paddle/phi/api/profiler/`); here XLA already placed the collectives,
so the honest model is to read them back out.

Reading the numbers honestly:

* **payload bytes, not wire bytes.** Each op is accounted at its
  LOGICAL payload: operand buffer bytes for all-reduce /
  reduce-scatter / all-to-all / collective-permute, RESULT buffer
  bytes for all-gather (the gathered buffer every participant ends up
  holding). Algorithm traffic (ring all-reduce moves ~2(n-1)/n x
  payload per link) is a backend scheduling detail; divide yourself if
  you need link-level numbers.
* **per-executed-program, counted once.** Like `cost.py` flops,
  while/scan bodies count ONCE, and collectives issued inside Pallas
  custom calls (manual-collective shard_map kernels) count ZERO — the
  IR walk is a LOWER bound under custom comm kernels.
* **axis attribution** maps each replica group's device entries to
  coordinates in the mesh's device array (entries are flat indices in
  row-major mesh order — the device-assignment order XLA uses for a
  mesh-sharded jit) and names the axes whose coordinate varies within
  a group. A fused collective spanning several axes reports a compound
  label ("data+model"); entries that don't fit the mesh land under
  "unattributed" rather than being dropped.

Consumers: `TracedFunction.comm_report()` (jit/api.py, beside
`cost_report()`), the serving `ProgramCache.comm_table()`, `bench.py`'s
`comm_bytes`/`comm_bytes_per_axis` JSON fields, the
`dryrun_multichip` evidence line, and the chip_hour COMM step
(tools/chip_comm.py). All analysis failures degrade to an error record
— accounting must never take down the program it describes.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["CollectiveOp", "CommReport", "parse_hlo_collectives",
           "parse_replica_groups", "compiled_comm", "lowered_comm",
           "jit_comm", "COLLECTIVE_KINDS", "UNATTRIBUTED"]

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

# axis label for replica groups whose entries don't map onto the mesh
UNATTRIBUTED = "unattributed"

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(
    r"\b(" + "|".join(sorted(_DTYPE_BYTES, key=len, reverse=True))
    + r")\[([0-9,]*)\]")
# the instruction head: "%name = <result shapes> <kind>[-start](..."
_INSTR_RE = re.compile(
    r"=\s*(?P<result>[^=]*?)\s(?P<kind>"
    + "|".join(COLLECTIVE_KINDS) + r")(?P<async>-start)?\(")
_EXPLICIT_GROUPS_RE = re.compile(r"\{\{[0-9,{} ]*\}\}|\{\}")
_IOTA_GROUPS_RE = re.compile(
    r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")


def _shape_bytes(text: str) -> int:
    """Total buffer bytes of every dtype[dims] shape token in `text`
    (a tuple shape simply contributes each element)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def parse_replica_groups(attr_text: str) -> Optional[List[Tuple[int, ...]]]:
    """Replica groups from an HLO attribute string. Handles the explicit
    form `{{0,1},{2,3}}`, the empty form `{}` (all participants in one
    group -> None, meaning "everyone"), and the iota form
    `[g,s]<=[dims]` / `[g,s]<=[dims]T(perm)` (v2 iota group lists:
    transpose iota(dims) by perm, reshape to g groups of s)."""
    m = _IOTA_GROUPS_RE.search(attr_text)
    if m is not None:
        out_dims = [int(x) for x in m.group(1).split(",")]
        reshape = [int(x) for x in m.group(2).split(",")]
        total = 1
        for d in reshape:
            total *= d
        flat = list(range(total))
        # build the transposed iota without numpy (stdlib-safe parse)
        if m.group(3):
            perm = [int(x) for x in m.group(3).split(",")]
            strides = [1] * len(reshape)
            for i in range(len(reshape) - 2, -1, -1):
                strides[i] = strides[i + 1] * reshape[i + 1]
            tdims = [reshape[p] for p in perm]
            tstrides = [strides[p] for p in perm]
            flat = []
            idx = [0] * len(tdims)
            for _ in range(total):
                flat.append(sum(i * s for i, s in zip(idx, tstrides)))
                for ax in range(len(tdims) - 1, -1, -1):
                    idx[ax] += 1
                    if idx[ax] < tdims[ax]:
                        break
                    idx[ax] = 0
        n_groups, group_size = out_dims[0], out_dims[-1]
        if len(out_dims) == 1:
            n_groups, group_size = 1, out_dims[0]
        return [tuple(flat[g * group_size:(g + 1) * group_size])
                for g in range(n_groups)]
    m = _EXPLICIT_GROUPS_RE.search(attr_text)
    if m is None:
        return None
    body = m.group(0)
    if body == "{}":
        return None
    groups = []
    for grp in re.findall(r"\{([0-9, ]+)\}", body):
        groups.append(tuple(int(x) for x in grp.replace(" ", "").split(",")
                            if x))
    return groups or None


class CollectiveOp:
    """One collective instruction found in the compiled HLO."""

    __slots__ = ("kind", "operand_bytes", "result_bytes", "groups",
                 "group_size", "axes")

    def __init__(self, kind, operand_bytes, result_bytes, groups,
                 group_size, axes=None):
        self.kind = kind
        self.operand_bytes = int(operand_bytes)
        self.result_bytes = int(result_bytes)
        self.groups = groups
        self.group_size = int(group_size)
        self.axes = axes        # tuple of mesh axis names, or None

    @property
    def payload_bytes(self) -> int:
        """The logical payload (module docstring): all-gather is
        accounted at the RESULT it materializes everywhere (operand x
        group size — computed that way so async `-start` tuple results
        don't double-count; the sync result equals it exactly), the
        rest at the operand buffer entering the collective."""
        if self.kind == "all-gather":
            if self.group_size > 0:
                return self.operand_bytes * self.group_size
            return self.result_bytes
        return self.operand_bytes

    @property
    def axis_label(self) -> str:
        if not self.axes:
            return UNATTRIBUTED
        return "+".join(self.axes)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "payload_bytes": self.payload_bytes,
                "operand_bytes": self.operand_bytes,
                "result_bytes": self.result_bytes,
                "group_size": self.group_size,
                "axis": self.axis_label}

    def __repr__(self):
        return (f"CollectiveOp({self.kind}, payload={self.payload_bytes}, "
                f"axis={self.axis_label}, groups of {self.group_size})")


def parse_hlo_collectives(hlo_text: str) -> List[CollectiveOp]:
    """Every collective instruction in an HLO module text. `-done` halves
    of async pairs carry no shape/group info of their own and are
    skipped (the `-start` is the accounted op)."""
    ops: List[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        # operand text: between the op's '(' and its matching ')'
        start = m.end()
        depth, end = 1, start
        while end < len(line) and depth:
            if line[end] == "(":
                depth += 1
            elif line[end] == ")":
                depth -= 1
            end += 1
        operand_text = line[start:end - 1]
        attr_text = line[end:]
        # metadata repeats the source op name; groups/pairs live in the
        # attribute tail only
        attr_text = attr_text.split("metadata=")[0]
        if kind == "collective-permute":
            pairs = parse_replica_groups(
                "".join(re.findall(r"source_target_pairs=(\{\{[0-9,{} ]*\}\})",
                                   attr_text)) or "{}")
            groups, group_size = pairs, 2
        else:
            groups = parse_replica_groups(attr_text)
            group_size = len(groups[0]) if groups else 0
        ops.append(CollectiveOp(
            kind=kind,
            operand_bytes=_shape_bytes(operand_text),
            result_bytes=_shape_bytes(m.group("result")),
            groups=groups, group_size=group_size))
    return ops


def _mesh_axis_attribution(mesh):
    """(axis_names, shape, id->coords fn) for a jax Mesh / ProcessMesh.
    Replica-group entries are flat indices in row-major mesh-device
    order (the device assignment of a mesh-sharded jit)."""
    jmesh = getattr(mesh, "jax_mesh", mesh)
    names = tuple(jmesh.axis_names)
    shape = tuple(jmesh.devices.shape)
    total = 1
    for d in shape:
        total *= d

    def coords(flat: int):
        if flat < 0 or flat >= total:
            return None
        c = []
        for d in reversed(shape):
            c.append(flat % d)
            flat //= d
        return tuple(reversed(c))

    return names, shape, coords


def attribute_axes(op: CollectiveOp, mesh) -> Optional[Tuple[str, ...]]:
    """The mesh axes a collective spans: axes whose coordinate varies
    within at least one replica group. None (unattributable) when any
    entry falls outside the mesh. groups=None means "every participant"
    -> every axis of size > 1."""
    names, shape, coords = _mesh_axis_attribution(mesh)
    if op.groups is None:
        return tuple(n for n, d in zip(names, shape) if d > 1) or None
    varying = set()
    for grp in op.groups:
        cs = []
        for entry in grp:
            c = coords(entry)
            if c is None:
                return None
            cs.append(c)
        for i in range(len(names)):
            if len({c[i] for c in cs}) > 1:
                varying.add(i)
    if not varying:
        return None
    return tuple(names[i] for i in sorted(varying))


class CommReport:
    """Collective traffic of ONE compiled program, attributed to mesh
    axes when a mesh is supplied."""

    def __init__(self, ops: Sequence[CollectiveOp], mesh=None):
        self.ops = list(ops)
        self.mesh_axes: Optional[Tuple[str, ...]] = None
        if mesh is not None:
            try:
                self.mesh_axes = tuple(
                    getattr(mesh, "jax_mesh", mesh).axis_names)
                for op in self.ops:
                    op.axes = attribute_axes(op, mesh)
            except Exception:
                self.mesh_axes = None

    # ---- aggregates ------------------------------------------------------
    @property
    def payload_bytes(self) -> int:
        return sum(op.payload_bytes for op in self.ops)

    def op_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.ops:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def bytes_per_axis(self) -> Dict[str, int]:
        """{axis label: payload bytes} — compound labels ("data+model")
        for fused multi-axis collectives, UNATTRIBUTED for groups that
        don't fit the mesh (or when no mesh was given)."""
        out: Dict[str, int] = {}
        for op in self.ops:
            k = op.axis_label
            out[k] = out.get(k, 0) + op.payload_bytes
        return out

    def counts_per_axis(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.ops:
            k = op.axis_label
            out[k] = out.get(k, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {"payload_bytes": self.payload_bytes,
                "op_counts": self.op_counts(),
                "bytes_per_axis": self.bytes_per_axis(),
                "counts_per_axis": self.counts_per_axis(),
                "mesh_axes": list(self.mesh_axes) if self.mesh_axes else None,
                "ops": [op.to_dict() for op in self.ops]}

    def __repr__(self):
        return (f"CommReport(payload_bytes={self.payload_bytes}, "
                f"per_axis={self.bytes_per_axis()})")


def _default_mesh():
    """The ambient hybrid mesh (mesh_scope override, else the fleet.init
    singleton) — the mesh whose axes the program was sharded over in
    every in-tree path."""
    try:
        from ..distributed.fleet.mpu import current_mesh
        return current_mesh()
    except Exception:
        return None


def compiled_comm(compiled, mesh=None) -> CommReport:
    """CommReport of a `jax.stages.Compiled`. Failures degrade to an
    empty report (accounting must never break the program)."""
    if mesh is None:
        mesh = _default_mesh()
    try:
        text = compiled.as_text()
    except Exception:
        return CommReport([], mesh=None)
    try:
        return CommReport(parse_hlo_collectives(text), mesh=mesh)
    except Exception:
        return CommReport([], mesh=None)


def lowered_comm(lowered, mesh=None) -> CommReport:
    """Compile a `jax.stages.Lowered` and account its collectives (a
    disk hit with the persistent compilation cache on)."""
    return compiled_comm(lowered.compile(), mesh=mesh)


def jit_comm(fn, *args, mesh=None, static_argnums=(), donate_argnums=(),
             **kwargs) -> CommReport:
    """Account an arbitrary function: jit -> lower -> compile ->
    CommReport. `args` may be ShapeDtypeStructs (`cost.shape_structs`)."""
    import jax
    jitted = jax.jit(fn, static_argnums=static_argnums,
                     donate_argnums=donate_argnums)
    return lowered_comm(jitted.lower(*args, **kwargs), mesh=mesh)
