"""Compile-event log: every compilation-shaped event, timestamped (ISSUE 11).

The training stack compiles in four places — `to_static` guard misses
(trace/retrace), dy2static AST rescues, eager-fallback guards, and the
serving `ProgramCache` — and until this module the only way to see a
compile storm was to diff `to_static_report()` between two points in
time. Here every such event lands in ONE bounded, stdlib-only log:

* `log_event(kind, name, duration_s, detail)` — called by jit/api.py
  (kinds `trace` / `retrace` / `ast_convert` / `eager_fallback`) and
  serving/program_cache.py (kind `program_compile`); `duration_s` is
  the wall time the event cost (for a trace: the first call's
  trace+compile+execute wall).
* the ring is bounded (`MAX_EVENTS`, oldest dropped and counted) and
  per-kind counters + duration totals are unbounded, so a long-lived
  process keeps an exact *rate* signal even after the window rolls —
  the alertable "compile storm" number is the counter delta per step,
  which `TrainingMonitor` records.

Consumers: `jit.to_static_report()` (the SOT-gap inventory gains the
compile timeline), `profiler.TrainingMonitor` (per-step event deltas +
Prometheus counters), `tools/train_report.py` (offline timeline).

Deliberately stdlib-only and jax-free: importing this module must never
claim the TPU grant (CLAUDE.md), and the serving ProgramCache logs
through it from inside engine hot paths.
"""
from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional

__all__ = ["log_event", "events", "counters", "duration_totals_s",
           "dropped", "reset", "generation", "KINDS", "MAX_EVENTS"]

# the closed vocabulary — consumers (train_report, monitor) render any
# kind they meet, but these are the ones the tree emits
KINDS = ("trace", "retrace", "ast_convert", "eager_fallback",
         "program_compile")

MAX_EVENTS = 512

_lock = threading.Lock()
_events: deque = deque(maxlen=MAX_EVENTS)
_counts: Counter = Counter()
_dur_totals: Dict[str, float] = {}
_dropped = [0]
_generation = [0]


def log_event(kind: str, name: str = "", duration_s: Optional[float] = None,
              detail: Optional[dict] = None):
    """Record one compile-shaped event. `name` identifies the function /
    program family; `detail` must be a small JSON-safe dict (guard-cache
    size, program key, error class — NOT tensors or tracebacks)."""
    rec = {"kind": str(kind), "name": str(name),
           # wall-clock epoch for cross-process correlation AND the
           # perf_counter ns the profiler/tracer clocks use, so the
           # event can be placed on a merged chrome trace
           "t_wall": time.time(),
           "ts_ns": time.perf_counter_ns()}
    if duration_s is not None:
        rec["duration_ms"] = round(float(duration_s) * 1e3, 3)
    if detail:
        rec["detail"] = dict(detail)
    with _lock:
        if len(_events) == _events.maxlen:
            _dropped[0] += 1
        _events.append(rec)
        _counts[rec["kind"]] += 1
        if duration_s is not None:
            _dur_totals[rec["kind"]] = (
                _dur_totals.get(rec["kind"], 0.0) + float(duration_s))
    return rec


def events() -> List[dict]:
    """The retained events, oldest first (copies — safe to mutate)."""
    with _lock:
        return [dict(r) for r in _events]


def counters() -> Dict[str, int]:
    """{kind: total events ever logged} — exact even after the ring
    rolled; the monitor's per-step deltas come from here."""
    with _lock:
        return dict(_counts)


def duration_totals_s() -> Dict[str, float]:
    """{kind: total seconds spent} over events that carried a duration."""
    with _lock:
        return dict(_dur_totals)


def dropped() -> int:
    """Events aged out of the bounded window."""
    return _dropped[0]


def generation() -> int:
    """Bumped by every reset() — delta consumers (TrainingMonitor)
    re-baseline on a generation change, so a mid-run
    `to_static_report(reset=True)` can never produce negative or
    silently-swallowed per-step deltas."""
    return _generation[0]


def reset():
    with _lock:
        _events.clear()
        _counts.clear()
        _dur_totals.clear()
        _dropped[0] = 0
        _generation[0] += 1
