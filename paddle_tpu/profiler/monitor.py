"""TrainingMonitor: the serving FlightRecorder's training counterpart
(ISSUE 11).

An always-on-when-attached bounded per-step ring over a training loop:
each `monitor.step(loss)` records fetch-synced step latency, the loss,
the gradient global norm, the learning rate, dispatch NaN-hook hits and
compile-event deltas (trace/retrace/eager-fallback/program-compile) —
so a NaN'd or slowed run carries its own postmortem, the way an engine
failure snapshot ships the flight recorder.

Timing contract (round-4 landmine, do not regress): over the axon relay
`jax.block_until_ready` does NOT block — only a host fetch
synchronizes. `step(loss)` therefore fetches the loss scalar FIRST and
stamps the clock AFTER the fetch returns: the recorded latency spans
the device work, not the async dispatch. A monitor-less loop pays
nothing: the only hook in the hot path (`Optimizer.step`) is one
module-global truthiness check, asserted allocation-free by
tests/test_training_monitor.py.

Three output surfaces, all derived from the same ring/counters:

* `snapshot()` — flat dict (counters + gauges + step-latency
  percentiles via the bounded-reservoir registry), rendered to
  Prometheus text by the SHARED exposition module
  (`profiler.exposition`, prefix `paddle_training`) under the same
  no-hand-maintained-name-list drift contract as serving;
* `export(path)` — a chrome-trace JSON (detailed mode adds one span
  per step on the `perf_counter_ns` clock `RecordEvent` uses, so the
  export merges with profiler host spans on ONE timeline) carrying the
  ring + compile-event log for `tools/train_report.py`;
* `Profiler.summary()` — `register()` adds the snapshot as a counter
  provider, like `ServingMetrics.register`.

Detailed mode (default OFF) is the only per-step allocation beyond the
ring dict: a chrome event per step. Everything recorded is JSON-safe.
"""
from __future__ import annotations

import json
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from . import compile_log

__all__ = ["TrainingMonitor", "active_monitor", "grad_global_norm",
           "TRAIN_PID", "PERCENTILE_WINDOW"]

# chrome-trace pid for training-step rows (serving request rows use 1,
# profiler host spans use os.getpid())
TRAIN_PID = 2

PERCENTILE_WINDOW = 1024

# the active-monitor stack: Optimizer.step's hook is `if _ACTIVE:` —
# one module-global truthiness check when no monitor is attached
_ACTIVE: List["TrainingMonitor"] = []


def active_monitor() -> Optional["TrainingMonitor"]:
    return _ACTIVE[-1] if _ACTIVE else None


# the shared nearest-rank percentile rule — one implementation for
# both observability stacks (serving reservoirs import it too)
from .exposition import percentile as _percentile  # noqa: E402


def _fetch_scalar(v) -> Optional[float]:
    """Host-fetch a scalar (Tensor / jax array / float) — the fetch IS
    the device sync (see module docstring). None-safe; a non-scalar or
    failed fetch records None rather than raising mid-train-loop."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    d = getattr(v, "_data", v)
    try:
        return float(np.asarray(d))
    except Exception:
        return None


def grad_global_norm(parameters) -> Optional[object]:
    """sqrt(sum ||g||^2) over parameters' live grad buffers as a LAZY
    jax scalar (fetch it to sync), fp32 accumulation. None when no
    concrete grads exist (e.g. inside a to_static trace, where grads
    are tracers and the python hook must not leak them)."""
    import jax
    import jax.numpy as jnp
    total = None
    for p in parameters:
        g = getattr(p, "_grad_buffer", None)
        if g is None:
            continue
        if isinstance(g, jax.core.Tracer):
            return None
        sq = jnp.sum(jnp.square(jnp.asarray(g).astype(jnp.float32)))
        total = sq if total is None else total + sq
    if total is None:
        return None
    return jnp.sqrt(total)


class TrainingMonitor:
    """Bounded per-step telemetry ring for a training loop.

    with TrainingMonitor(optimizer=opt).watch(step_fn) as mon:
        for batch in loader:
            loss = step_fn(*batch)
            mon.step(loss, tokens=batch_tokens)
    mon.snapshot(); mon.export("train_trace.json")
    """

    def __init__(self, max_steps: int = 512, optimizer=None,
                 detailed: bool = False, name: str = "training",
                 track_grad_norm: bool = True):
        self.name = name
        self.detailed = bool(detailed)
        self.track_grad_norm = bool(track_grad_norm)
        self._optimizer = optimizer
        self._traced = None
        self._ring: deque = deque(maxlen=int(max_steps))
        self._chrome: deque = deque(maxlen=int(max_steps))
        self.counters: Dict[str, int] = {
            "steps": 0,
            "tokens": 0,
            "nan_checks": 0,       # dispatch NaN-hook evaluations seen
            "nan_hits": 0,         # NaN/Inf detections (the alert)
            "traces": 0,           # to_static first compiles
            "retraces": 0,         # guard misses on a warm cache
            "ast_converts": 0,     # dy2static rescues
            "eager_fallbacks": 0,  # graph breaks -> eager
            "program_compiles": 0,  # serving ProgramCache compiles
        }
        self._latency = deque(maxlen=PERCENTILE_WINDOW)   # seconds
        self._t_last: Optional[int] = None
        self.last_loss: Optional[float] = None
        self.last_grad_norm: Optional[float] = None
        self.last_lr: Optional[float] = None
        # pending per-step context pushed by hooks (Optimizer.step)
        self._pending: Dict[str, object] = {}
        self._last_compile = compile_log.counters()
        self._last_compile_gen = compile_log.generation()
        self._last_nan = self._nan_stats()
        self._last_nan_gen = self._nan_gen()
        self._registered = False

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "TrainingMonitor":
        if self not in _ACTIVE:
            _ACTIVE.append(self)
        self._t_last = None
        return self

    def stop(self) -> "TrainingMonitor":
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        return self

    def __enter__(self) -> "TrainingMonitor":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def watch(self, traced) -> "TrainingMonitor":
        """Attach the TracedFunction driving the loop: its donation
        mode and fallback/program counts become snapshot gauges and the
        per-step `retraced` flag."""
        self._traced = traced
        return self

    # ---- hooks (called by Optimizer.step when this monitor is active) ----
    def note(self, **kw):
        """Stash per-step context (lr, grad_norm — possibly a LAZY jax
        scalar) for the next `step()` call to fetch and record."""
        self._pending.update(kw)

    # ---- the per-step record ---------------------------------------------
    @staticmethod
    def _nan_stats() -> Dict[str, int]:
        try:
            from ..utils import nan_inf
            return nan_inf.nan_stats()
        except Exception:
            return {"checks": 0, "hits": 0}

    @staticmethod
    def _nan_gen() -> int:
        try:
            from ..utils import nan_inf
            return nan_inf.nan_stats_generation()
        except Exception:
            return 0

    def step(self, loss=None, *, grad_norm=None, lr=None, tokens=None):
        """Record one training step (call once per iteration, after the
        step ran). Fetches the loss (and any pending grad norm) BEFORE
        stamping the clock — the fetch is the sync."""
        loss_v = _fetch_scalar(loss)
        if grad_norm is None:
            grad_norm = self._pending.pop("grad_norm", None)
        gn_v = _fetch_scalar(grad_norm)
        if lr is None:
            lr = self._pending.pop("lr", None)
            if lr is None and self._optimizer is not None:
                try:
                    lr = self._optimizer.get_lr()
                except Exception:
                    lr = None
        now = time.perf_counter_ns()
        dur_ns = None if self._t_last is None else now - self._t_last
        self._t_last = now
        n = self.counters["steps"]
        self.counters["steps"] += 1
        if tokens:
            self.counters["tokens"] += int(tokens)
        # compile-event + NaN-hook deltas since the previous step. The
        # shared sources can be RESET mid-run (to_static_report(
        # reset=True) clears the compile log, reset_nan_stats() the NaN
        # counters): their reset GENERATION re-baselines the deltas to
        # zero, and a residual total-below-baseline also counts from
        # zero — a Prometheus counter must never go backwards.
        gen = compile_log.generation()
        if gen != self._last_compile_gen:
            self._last_compile = {}
            self._last_compile_gen = gen
        comp = compile_log.counters()
        comp_delta = {}
        for k, v in comp.items():
            prev = self._last_compile.get(k, 0)
            d = v - prev if v >= prev else v
            if d:
                comp_delta[k] = d
        self._last_compile = comp
        for kind, d in comp_delta.items():
            key = {"trace": "traces", "retrace": "retraces",
                   "ast_convert": "ast_converts",
                   "eager_fallback": "eager_fallbacks",
                   "program_compile": "program_compiles"}.get(kind)
            if key is not None:
                self.counters[key] += d
        nan_gen = self._nan_gen()
        if nan_gen != self._last_nan_gen:
            self._last_nan = {"checks": 0, "hits": 0}
            self._last_nan_gen = nan_gen
        nan = self._nan_stats()

        def _delta(cur, prev):          # reset-proof (see above)
            return cur - prev if cur >= prev else cur
        nan_checks = _delta(nan.get("checks", 0),
                            self._last_nan.get("checks", 0))
        nan_hits = _delta(nan.get("hits", 0), self._last_nan.get("hits", 0))
        self._last_nan = nan
        self.counters["nan_checks"] += nan_checks
        self.counters["nan_hits"] += nan_hits

        rec = {"step": n, "t1_ns": now,
               "dur_ms": None if dur_ns is None else round(dur_ns / 1e6, 4),
               "loss": loss_v, "grad_norm": gn_v,
               "lr": None if lr is None else float(lr),
               "tokens": None if tokens is None else int(tokens)}
        if nan_hits:
            rec["nan_hits"] = nan_hits
        if comp_delta:
            rec["compile_events"] = comp_delta
            rec["retraced"] = bool(comp_delta.get("trace")
                                   or comp_delta.get("retrace"))
        self._ring.append(rec)
        if dur_ns is not None:
            self._latency.append(dur_ns / 1e9)
        self.last_loss = loss_v
        self.last_grad_norm = gn_v
        self.last_lr = rec["lr"]
        self._pending.clear()
        if self.detailed and dur_ns is not None:
            ev = {"name": "train_step", "ph": "X", "cat": "training",
                  "ts": (now - dur_ns) / 1e3, "dur": dur_ns / 1e3,
                  "pid": TRAIN_PID, "tid": 0,
                  "args": {"step": n, "loss": loss_v}}
            self._chrome.append(ev)
        return rec

    # ---- views -----------------------------------------------------------
    def records(self) -> List[dict]:
        """The retained step records, oldest first (copies)."""
        return [dict(r) for r in self._ring]

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        return {f"p{q}": _percentile(self._latency, q)
                for q in (50, 90, 99)}

    def snapshot(self) -> dict:
        """Flat counters+gauges dict — the Prometheus/summary surface.
        None-valued gauges are omitted (the exposition rule: no honest
        value, no sample)."""
        snap = dict(self.counters)
        snap["ring_steps"] = len(self._ring)
        snap["detailed"] = self.detailed
        snap["compile_events_dropped"] = compile_log.dropped()
        if self.last_loss is not None:
            snap["last_loss"] = self.last_loss
        if self.last_grad_norm is not None:
            snap["last_grad_norm"] = self.last_grad_norm
        if self.last_lr is not None:
            snap["last_lr"] = self.last_lr
        tr = self._traced
        if tr is not None:
            snap["watched_donate"] = bool(getattr(tr, "_donate", False))
            snap["watched_programs"] = len(getattr(tr, "_cache", ()))
            snap["watched_fallbacks"] = int(
                getattr(tr, "_fallback_count", 0))
        for q, v in self.latency_percentiles().items():
            if v is not None:
                snap[f"step_latency_{q}_ms"] = round(v * 1e3, 3)
        return snap

    summary = snapshot

    def prometheus_text(self, *, prefix: str = "paddle_training",
                        labels: Optional[dict] = None,
                        emit_type: bool = True) -> str:
        """snapshot() through the SHARED exposition renderer — keys in
        the counters dict are typed counter, everything else gauge; the
        drift test asserts the bijection both ways."""
        from .exposition import prometheus_lines
        lines = prometheus_lines(self.snapshot(),
                                 counter_keys=set(self.counters),
                                 prefix=prefix, labels=labels,
                                 emit_type=emit_type)
        return "\n".join(lines) + "\n" if lines else ""

    # ---- export ----------------------------------------------------------
    def chrome_events(self) -> List[dict]:
        events: List[dict] = []
        if self._chrome:
            events.append({"name": "process_name", "ph": "M",
                           "pid": TRAIN_PID,
                           "args": {"name": "training steps"}})
            events.extend(dict(e) for e in self._chrome)
        return events

    def export(self, path: Optional[str] = None,
               include_profiler: bool = True) -> dict:
        """One document for tools/train_report.py: chrome spans
        (detailed mode; merged with profiler RecordEvent host spans on
        the shared perf_counter clock) + the step ring + the
        compile-event log + the snapshot."""
        events = self.chrome_events()
        if include_profiler:
            import os
            from . import host_events
            host = host_events()
            if host:
                events.append({"name": "process_name", "ph": "M",
                               "pid": os.getpid(),
                               "args": {"name": "host spans"}})
            for e in host:
                events.append({"name": e["name"], "ph": "X",
                               "cat": e["type"], "ts": e["ts"] / 1e3,
                               "dur": e["dur"] / 1e3,
                               "pid": os.getpid(), "tid": e["tid"]})
        doc = {"displayTimeUnit": "ms", "traceEvents": events,
               "trainingMonitor": {
                   "snapshot": self.snapshot(),
                   "records": self.records(),
                   "compile_events": compile_log.events(),
                   "compile_counters": compile_log.counters(),
               }}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    # ---- profiler integration -------------------------------------------
    def register(self) -> "TrainingMonitor":
        """Expose the snapshot through Profiler.summary() (the
        ServingMetrics.register pattern)."""
        from . import register_counter_provider
        register_counter_provider(self.name, self.snapshot)
        self._registered = True
        return self

    def unregister(self):
        if self._registered:
            from . import unregister_counter_provider
            unregister_counter_provider(self.name)
            self._registered = False
