"""Prometheus-style text exposition of snapshot dicts (ISSUE 10/11).

Born in `serving/` for `ServingMetrics` (ISSUE 10), generalized here in
ISSUE 11 so the TRAINING side (`profiler.TrainingMonitor`) scrapes
through the same renderer — `paddle_tpu.serving.exposition` remains as
a back-compat shim. Registry-driven by construction: the renderer walks
a LIVE `snapshot()` dict (the same no-hand-maintained-key-list contract
the snapshot itself has with the counters dict and the reservoir
registry), so the exposition can never disagree with `snapshot()` —
every key surfaces, nothing is filtered by name, and a new
counter/gauge/reservoir appears in the scrape the moment it appears in
the snapshot. tests/test_metrics_exposition.py and
tests/test_training_monitor.py assert the bijection both ways (the
drift tests).

Rendering rules (one rule per VALUE type, never per key):

* numeric (int/float/bool) — `<prefix>_<key>{labels} <value>`, typed
  `counter` when the key lives in the metrics object's counters dict,
  `gauge` otherwise;
* string (e.g. `kv_dtype`) — an info-style gauge
  `<prefix>_<key>_info{<key>="<value>",labels} 1` (the textual value
  becomes a label, Prometheus has no string samples);
* dict (e.g. a fleet summary's `replica_states`) — one line per entry
  with the entry key as a label;
* None — omitted (a percentile with no samples has no honest value).

`Fleet.prometheus_text()` layers per-replica labels on top; the
`FleetServer.metrics_text()` hook is the scrape endpoint body for the
future HTTP transport.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional

__all__ = ["render_prometheus", "prometheus_lines", "metric_name",
           "sanitize_metric_name", "sanitize_label_value",
           "parse_exposition_names", "percentile"]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_]")
# a sample line: name{optional labels} value
_SAMPLE_RE = re.compile(r"^([a-zA-Z_][a-zA-Z0-9_]*)(\{[^}]*\})? \S+$")


def percentile(samples, q):
    """Nearest-rank percentile over a small window (no numpy needed) —
    THE percentile rule for both observability stacks
    (`ServingMetrics` reservoirs and the `TrainingMonitor` latency
    ring; the stdlib-only tools/ reporters carry their own copy by
    construction). Returns None on an empty window."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def sanitize_metric_name(key: str) -> str:
    name = _NAME_BAD.sub("_", str(key))
    if name and name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label_value(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def metric_name(prefix: str, key: str) -> str:
    return f"{sanitize_metric_name(prefix)}_{sanitize_metric_name(key)}"


def _label_str(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{sanitize_metric_name(k)}="'
                     f'{sanitize_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def prometheus_lines(snapshot: dict, *, counter_keys: Iterable[str] = (),
                     prefix: str = "paddle_serving",
                     labels: Optional[Dict[str, str]] = None,
                     emit_type: bool = True) -> List[str]:
    """Render one snapshot dict to exposition lines (no trailing
    newline). `counter_keys` marks which keys get `# TYPE ... counter`;
    everything else is a gauge. Set `emit_type=False` for a secondary
    rendering of the same metrics (e.g. per-replica lines after the
    merged block) — Prometheus allows one TYPE line per metric name."""
    counter_keys = set(counter_keys)
    lines: List[str] = []
    for key, value in snapshot.items():
        if value is None:
            continue
        name = metric_name(prefix, key)
        if isinstance(value, bool):
            value = int(value)
        if isinstance(value, (int, float)):
            typ = "counter" if key in counter_keys else "gauge"
            if emit_type:
                lines.append(f"# TYPE {name} {typ}")
            lines.append(f"{name}{_label_str(labels)} {value}")
        elif isinstance(value, str):
            name += "_info"
            if emit_type:
                lines.append(f"# TYPE {name} gauge")
            info = dict(labels or {})
            info[sanitize_metric_name(key)] = value
            lines.append(f"{name}{_label_str(info)} 1")
        elif isinstance(value, dict):
            if emit_type:
                lines.append(f"# TYPE {name} gauge")
            for sub, sv in value.items():
                ls = dict(labels or {})
                ls[sanitize_metric_name(key).rstrip("s") or key] = sub
                if isinstance(sv, (int, float)) and \
                        not isinstance(sv, bool):
                    lines.append(f"{name}{_label_str(ls)} {sv}")
                else:
                    ls["value"] = str(sv)
                    lines.append(f"{name}{_label_str(ls)} 1")
        else:
            # unknown value type: surface it as an info label rather
            # than silently dropping a snapshot key (the drift test
            # would catch a drop)
            name += "_info"
            if emit_type:
                lines.append(f"# TYPE {name} gauge")
            info = dict(labels or {})
            info[sanitize_metric_name(key)] = sanitize_label_value(value)
            lines.append(f"{name}{_label_str(info)} 1")
    return lines


def render_prometheus(snapshot: dict, *, counter_keys: Iterable[str] = (),
                      prefix: str = "paddle_serving",
                      labels: Optional[Dict[str, str]] = None) -> str:
    """One snapshot as Prometheus exposition text (trailing newline)."""
    return "\n".join(prometheus_lines(
        snapshot, counter_keys=counter_keys, prefix=prefix,
        labels=labels)) + "\n"


def parse_exposition_names(text: str) -> set:
    """Metric names present in an exposition text — the drift test's
    reverse direction (and a format sanity check: every non-comment
    line must parse as `name{labels} value`)."""
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        names.add(m.group(1))
    return names
