"""Profiler subsystem.

Parity: reference unified profiler (`paddle/fluid/platform/profiler/
profiler.h:47`, python `python/paddle/profiler/profiler.py:358`):
  * `RecordEvent` — instrumented host spans (reference
    `phi/api/profiler/event_tracing.h:32`), here also emitted as
    jax.profiler TraceAnnotations so they appear on the device timeline;
  * `Profiler` with `make_scheduler(closed/ready/record, repeat)` state
    machine, start/stop/step, chrome-trace export and `summary()` tables
    (reference `profiler_statistic.py`);
  * `benchmark()` step timer with ips/latency stats (reference
    `python/paddle/profiler/timer.py`).

TPU-native: the device side is jax.profiler (XLA/TPU trace -> perfetto/
tensorboard); the host side is a lightweight span recorder. Chrome-trace
export writes the host spans; the device trace directory sits next to it.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import ContextDecorator
from enum import Enum
from typing import Callable, Iterable, Optional

__all__ = ["ProfilerState", "ProfilerTarget", "TracerEventType",
           "RecordEvent", "Profiler", "make_scheduler", "benchmark",
           "export_chrome_tracing", "load_profiler_result",
           "register_counter_provider", "unregister_counter_provider",
           "counters", "default_log_dir", "host_events",
           "PROFILER_LOG_DIR_ENV"]

# Where chrome-trace exports land when no explicit log_dir is given:
# the env var overrides, the default keeps everything in one gitignored
# directory instead of littering the repo root / CWD.
PROFILER_LOG_DIR_ENV = "PADDLE_TPU_PROFILER_DIR"


def default_log_dir() -> str:
    """The profiler's export directory: `Profiler(log_dir=...)` wins,
    then $PADDLE_TPU_PROFILER_DIR, then ./profiler_log (gitignored)."""
    return os.environ.get(PROFILER_LOG_DIR_ENV) or "./profiler_log"


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1      # accepted for API compat; maps to the device target
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class TracerEventType(Enum):
    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    Forward = 3
    Backward = 4
    Optimization = 5
    Communication = 6
    PythonOp = 7
    UserDefined = 8


class _HostTracer:
    """Collects RecordEvent spans (thread-safe, per-thread nesting)."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()
        self.enabled = False

    def add(self, name, etype, start_ns, end_ns, tid):
        if not self.enabled:
            return
        with self._lock:
            self.events.append({"name": name, "type": etype.name,
                                "ts": start_ns, "dur": end_ns - start_ns,
                                "tid": tid})


_tracer = _HostTracer()


def host_events() -> list:
    """The recorded RecordEvent host spans (a copy) — the accessor the
    serving RequestTracer merges into its chrome-trace export so host
    work and request lifecycles share one timeline."""
    return list(_tracer.events)

# Counter providers: subsystems (e.g. serving.metrics) register a zero-arg
# callable returning {counter: value}; Profiler.summary() appends the live
# values and counters() exposes them programmatically.
_counter_providers: dict = {}


def register_counter_provider(name: str, fn):
    _counter_providers[name] = fn


def unregister_counter_provider(name: str):
    _counter_providers.pop(name, None)


def counters() -> dict:
    """{provider: {counter: value}} from every registered provider."""
    out = {}
    for name, fn in list(_counter_providers.items()):
        try:
            out[name] = fn()
        except Exception as e:        # a dead provider must not sink summary()
            out[name] = {"error": repr(e)}
    return out


class RecordEvent(ContextDecorator):
    """Host span; shows on the device timeline via TraceAnnotation.

    Parity: paddle.profiler.RecordEvent (event_tracing.h:32 emission
    points are the generated ad_funcs; here ops.dispatch hooks this when
    FLAGS_benchmark or an active profiler asks for op spans)."""

    def __init__(self, name: str,
                 event_type: TracerEventType = TracerEventType.UserDefined):
        self.name = name
        self.event_type = event_type
        self._ann = None
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        if _tracer.enabled:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None

    def end(self):
        if self._t0 is None:
            return
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        _tracer.add(self.name, self.event_type, self._t0,
                    time.perf_counter_ns(), threading.get_ident())
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Parity: paddle.profiler.make_scheduler — step-indexed state fn."""
    period = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return fn


def _default_on_ready(prof):
    path = prof.log_dir or default_log_dir()
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"paddle_tpu_trace_{int(time.time())}.json")
    prof.export(out)


class Profiler:
    """Parity: paddle.profiler.Profiler (profiler.py:358).

    with Profiler(scheduler=make_scheduler(...)) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    p.summary()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, log_dir=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU,
                                                      ProfilerTarget.TPU]
        if scheduler is None:
            self.scheduler = lambda step: ProfilerState.RECORD
        elif callable(scheduler):
            self.scheduler = scheduler
        else:  # (start, end) tuple form
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=max(lo, 0), ready=0,
                                            record=hi - lo, repeat=1)
        self.on_trace_ready = on_trace_ready or _default_on_ready
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._device_tracing = False
        self._step_records = []
        self._last_step_t = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        benchmark().begin()
        if self.timer_only:
            return
        self.current_state = self.scheduler(self.step_num)
        self._transition(ProfilerState.CLOSED, self.current_state)
        return self

    def stop(self):
        benchmark().end()
        if self.timer_only:
            return
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_tracing()
            self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        benchmark().step(num_samples)
        now = time.perf_counter_ns()
        if self._last_step_t is not None:
            self._step_records.append(now - self._last_step_t)
        self._last_step_t = now
        if self.timer_only:
            self.step_num += 1
            return
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        self._transition(prev, self.current_state)

    def _transition(self, prev, new):
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev not in recording and new in recording:
            try:
                self._start_tracing()
            except Exception:
                # roll back so this profiler's stop()/__exit__ cannot tear
                # down the OTHER profiler's active recording
                self.current_state = ProfilerState.CLOSED
                raise
        elif prev in recording and new not in recording:
            self._stop_tracing()
            self.on_trace_ready(self)

    def _start_tracing(self):
        if _tracer.enabled:
            # the module-global tracer supports ONE active profiler; a
            # silent second start would clear the first profiler's spans
            raise RuntimeError(
                "another Profiler is already recording; stop it first "
                "(only one active Profiler is supported)")
        _tracer.enabled = True
        _tracer.events = []
        if any(t in (ProfilerTarget.TPU, ProfilerTarget.GPU)
               for t in self.targets):
            try:
                import jax
                d = self.log_dir or default_log_dir()
                os.makedirs(d, exist_ok=True)
                jax.profiler.start_trace(d)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False

    def _stop_tracing(self):
        _tracer.enabled = False
        if self._device_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results ----------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Chrome-trace JSON of the host spans (device trace lives in the
        jax trace dir). Parity: export_chrome_tracing."""
        events = [{"name": e["name"], "ph": "X", "cat": e["type"],
                   "ts": e["ts"] / 1e3, "dur": e["dur"] / 1e3,
                   "pid": os.getpid(), "tid": e["tid"]}
                  for e in _tracer.events]
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated host-span table (name, calls, total/avg/max).
        Parity: profiler_statistic.py summary tables."""
        div = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}[time_unit]
        agg = {}
        for e in _tracer.events:
            a = agg.setdefault(e["name"], {"calls": 0, "total": 0,
                                           "max": 0, "type": e["type"]})
            a["calls"] += 1
            a["total"] += e["dur"]
            a["max"] = max(a["max"], e["dur"])
        rows = sorted(agg.items(), key=lambda kv: -kv[1]["total"])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                 f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}"]
        lines.append("-" * len(lines[0]))
        for name, a in rows:
            lines.append(
                f"{name[:39]:<40}{a['calls']:>8}"
                f"{a['total'] / div:>14.4f}"
                f"{a['total'] / a['calls'] / div:>12.4f}"
                f"{a['max'] / div:>12.4f}")
        if self._step_records:
            import statistics
            sr = [x / 1e6 for x in self._step_records]
            lines.append("")
            lines.append(
                f"steps: {len(sr)}  avg {statistics.mean(sr):.3f} ms  "
                f"p50 {statistics.median(sr):.3f} ms  "
                f"max {max(sr):.3f} ms")
        ctrs = counters()
        if ctrs:
            lines.append("")
            for prov, vals in sorted(ctrs.items()):
                pairs = "  ".join(f"{k}={v}" for k, v in vals.items())
                lines.append(f"[{prov}] {pairs}")
        table = "\n".join(lines)
        print(table)
        return table

    @property
    def events(self):
        return list(_tracer.events)


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Parity: paddle.profiler.export_chrome_tracing — on_trace_ready
    factory writing into dir_name."""
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        prof.export(os.path.join(
            dir_name, f"{name}_{int(time.time() * 1000)}.json"))
    return handler


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# benchmark timer (parity: python/paddle/profiler/timer.py)
# ---------------------------------------------------------------------------

class _Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._steps = []
        self._samples = []
        self._t0 = None
        self._running = False

    def begin(self):
        self.reset()
        self._running = True
        self._t0 = time.perf_counter()

    def step(self, num_samples=None):
        if not self._running:
            return
        now = time.perf_counter()
        self._steps.append(now - self._t0)
        self._samples.append(num_samples)
        self._t0 = now

    def step_info(self, unit="samples"):
        if not self._steps:
            return "no steps recorded"
        import statistics
        avg = statistics.mean(self._steps)
        line = (f"avg_batch_cost: {avg * 1000:.3f} ms, "
                f"p50: {statistics.median(self._steps) * 1000:.3f} ms")
        vals = [s for s in self._samples if s]
        if vals:
            total = sum(vals)
            ips = total / sum(self._steps)
            line += f", ips: {ips:.2f} {unit}/s"
        return line

    def end(self):
        self._running = False

    @property
    def num_steps(self):
        return len(self._steps)


_benchmark = _Benchmark()


def benchmark() -> _Benchmark:
    """Parity: paddle.profiler.utils.benchmark() global step timer."""
    return _benchmark


import enum as _enum


class SortedKeys(_enum.Enum):
    """Summary sort keys (parity: profiler.SortedKeys)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(_enum.Enum):
    """Summary table views (parity: profiler.SummaryView)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


# ---------------------------------------------------------------------------
# training observability (ISSUE 11): cost accounting, compile-event log,
# and the TrainingMonitor — submodules kept import-light (no jax at
# module level) so loading the profiler never touches a backend.
# ---------------------------------------------------------------------------
from . import compile_log            # noqa: E402
from . import cost                   # noqa: E402
from . import exposition             # noqa: E402
from .monitor import (TrainingMonitor, active_monitor,  # noqa: E402
                      grad_global_norm)

__all__ += ["TrainingMonitor", "active_monitor", "grad_global_norm",
            "compile_log", "cost", "exposition"]


def export_protobuf(profiler_result, path):
    """Serialize a profiler result (parity: profiler.export_protobuf —
    the reference dumps its own proto; this build writes the same JSON
    span list load_profiler_result reads back)."""
    import json
    data = profiler_result.events if hasattr(profiler_result, "events") \
        else profiler_result
    with open(path, "w") as f:
        json.dump(data, f)
    return path
