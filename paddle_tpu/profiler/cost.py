"""XLA cost/memory accounting per compiled program (ISSUE 11).

Until this PR every FLOPs / HBM-bytes / MFU claim in the tree was a
hand-maintained formula (`bench.py::llama_step_flops`, BASELINE.md's
`adamw_update_bytes` sizing tables) — honest the day it was written,
unverifiable after. XLA already computes the ground truth at compile
time: `lowered.compile().cost_analysis()` (flops, transcendentals,
per-operand bytes accessed) and `.memory_analysis()`
(argument/output/temp/alias buffer sizes). This module turns those into
one structured `ProgramCost`, and the hand formulas become
CROSS-CHECKED claims (tests/test_profiler_cost.py fails on drift).

Reading the numbers honestly:

* `flops` counts the HLO module's arithmetic. While/scan BODIES ARE
  COUNTED ONCE, not per trip — so programs that hide matmuls inside
  `lax.scan`/Pallas-interpret kernels (the CPU flash-attention path)
  UNDERCOUNT, and custom-call kernels (real Pallas on TPU) count zero.
  Analytic MFU is therefore a LOWER bound whenever custom kernels are
  in the program; the FLOPs cross-check pins the pure-XLA sdpa path
  where the count is exact (measured 1.003x of the hand formula on the
  flagship config).
* `bytes_accessed` is XLA's per-op operand+result sum — it counts
  intermediate fusion traffic and overlaps, NOT minimal HBM traffic
  (measured 1.5x the roofline bytes on the AdamW update). For
  roofline/bytes claims use `io_bytes` (argument + output buffer
  sizes from memory_analysis): for a bytes-bound program that reads
  every input once and writes every output once it IS the roofline
  number — it reproduces `adamw_update_bytes` exactly.
* `peak_bytes` = arguments + outputs + temps - donation aliases: the
  live-buffer bound XLA budgeted, the "does this config fit HBM"
  number (`bench.py` reports it as `peak_hbm_bytes`).

Consumers: `TracedFunction.cost_report()` (jit/api.py), the serving
`ProgramCache.cost_table()`, `bench.py`'s JSON line, and the
chip_hour COST_MFU step.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["ProgramCost", "compiled_cost", "lowered_cost", "jit_cost",
           "shape_structs", "peak_flops_per_chip", "analytic_mfu",
           "PEAK_FLOPS"]

# bf16 peak FLOP/s per chip by device kind — the table bench.py carries
# (tests assert the two agree; bench.py must stay import-light because
# its supervisor never touches the package).
PEAK_FLOPS = {
    "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
    "v5p": 459e12, "v5": 459e12,
    "v4": 275e12,
    "v6": 918e12, "v6e": 918e12, "trillium": 918e12,
    "cpu": 1e12,  # nominal, CPU is correctness-only
}


def peak_flops_per_chip(device_kind: str) -> float:
    kind = str(device_kind).lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 197e12


class ProgramCost:
    """Structured cost/memory accounting of ONE compiled program."""

    __slots__ = ("flops", "transcendentals", "bytes_accessed",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "alias_bytes", "generated_code_bytes")

    def __init__(self, *, flops=0.0, transcendentals=0.0,
                 bytes_accessed=0.0, argument_bytes=0, output_bytes=0,
                 temp_bytes=0, alias_bytes=0, generated_code_bytes=0):
        self.flops = float(flops)
        self.transcendentals = float(transcendentals)
        self.bytes_accessed = float(bytes_accessed)
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.alias_bytes = int(alias_bytes)
        self.generated_code_bytes = int(generated_code_bytes)

    # ---- derived ---------------------------------------------------------
    @property
    def io_bytes(self) -> int:
        """Read-every-input-once + write-every-output-once traffic — the
        roofline bytes for a bandwidth-bound program (matches
        `adamw_update_bytes` on the optimizer step)."""
        return self.argument_bytes + self.output_bytes

    @property
    def peak_bytes(self) -> int:
        """Live-buffer bound: args + outputs + temps - donation aliases."""
        return (self.argument_bytes + self.output_bytes
                + self.temp_bytes - self.alias_bytes)

    def mfu(self, dt_s: float, peak_flops: Optional[float] = None,
            device_kind: Optional[str] = None) -> Optional[float]:
        """Analytic MFU of one execution taking `dt_s` seconds."""
        if peak_flops is None:
            peak_flops = peak_flops_per_chip(
                device_kind if device_kind is not None
                else _default_device_kind())
        if dt_s <= 0 or peak_flops <= 0:
            return None
        return self.flops / dt_s / peak_flops

    def hbm_gbps(self, dt_s: float) -> Optional[float]:
        """io_bytes / time — the achieved roofline GB/s."""
        if dt_s <= 0:
            return None
        return self.io_bytes / dt_s / 1e9

    def to_dict(self) -> dict:
        return {"flops": self.flops,
                "transcendentals": self.transcendentals,
                "bytes_accessed": self.bytes_accessed,
                "io_bytes": self.io_bytes,
                "peak_bytes": self.peak_bytes,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "alias_bytes": self.alias_bytes,
                "generated_code_bytes": self.generated_code_bytes}

    def __repr__(self):
        return (f"ProgramCost(flops={self.flops:.4g}, "
                f"io_bytes={self.io_bytes}, peak_bytes={self.peak_bytes})")


def _default_device_kind() -> str:
    import jax
    dev = jax.devices()[0]
    return getattr(dev, "device_kind", dev.platform)


def analytic_mfu(flops: float, dt_s: float,
                 peak_flops: Optional[float] = None,
                 device_kind: Optional[str] = None) -> Optional[float]:
    """MFU from already-known flops (e.g. a hand formula) — same peak
    table as ProgramCost.mfu so the two are directly comparable."""
    if peak_flops is None:
        peak_flops = peak_flops_per_chip(
            device_kind if device_kind is not None
            else _default_device_kind())
    if dt_s <= 0 or peak_flops <= 0:
        return None
    return float(flops) / dt_s / peak_flops


def compiled_cost(compiled) -> ProgramCost:
    """ProgramCost of a `jax.stages.Compiled` (or anything exposing
    cost_analysis()/memory_analysis()). Absent analyses (some backends
    return None) degrade to zeros rather than raising — a cost report
    must never take down the program it describes."""
    ca: Dict[str, Any] = {}
    try:
        raw = compiled.cost_analysis()
        # jax 0.4.x returns [dict] (one per partition), newer a dict
        if isinstance(raw, (list, tuple)):
            raw = raw[0] if raw else {}
        ca = dict(raw or {})
    except Exception:
        pass
    kw = {"flops": ca.get("flops", 0.0) or 0.0,
          "transcendentals": ca.get("transcendentals", 0.0) or 0.0,
          "bytes_accessed": ca.get("bytes accessed", 0.0) or 0.0}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        kw.update(
            argument_bytes=getattr(ma, "argument_size_in_bytes", 0),
            output_bytes=getattr(ma, "output_size_in_bytes", 0),
            temp_bytes=getattr(ma, "temp_size_in_bytes", 0),
            alias_bytes=getattr(ma, "alias_size_in_bytes", 0),
            generated_code_bytes=getattr(
                ma, "generated_code_size_in_bytes", 0))
    return ProgramCost(**kw)


def lowered_cost(lowered) -> ProgramCost:
    """Compile a `jax.stages.Lowered` and account it. With the
    persistent compilation cache on (bench.py enables it), re-compiling
    an already-seen program is a disk hit."""
    return compiled_cost(lowered.compile())


def shape_structs(tree):
    """Abstract a pytree of arrays to ShapeDtypeStructs (non-array
    leaves pass through), so a program can be re-lowered for accounting
    without holding or moving any data."""
    import jax

    def _abs(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return leaf
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return jax.tree_util.tree_map(_abs, tree)


def jit_cost(fn, *args, static_argnums=(), donate_argnums=(),
             **kwargs) -> ProgramCost:
    """Account an arbitrary function: jit -> lower(*args) -> compile ->
    ProgramCost. `args` may be concrete arrays or ShapeDtypeStructs
    (pass through `shape_structs` to avoid materializing inputs)."""
    import jax
    jitted = jax.jit(fn, static_argnums=static_argnums,
                     donate_argnums=donate_argnums)
    return lowered_cost(jitted.lower(*args, **kwargs))
