"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Top-level namespace parity: reference `python/paddle/__init__.py` — Tensor,
creation/math/manipulation ops, nn, optimizer, amp, autograd, io,
distributed, jit, vision, profiler.
"""
from __future__ import annotations

import os

# 64-bit dtypes on (paddle's default int dtype is int64). Floats still default
# to float32 via get_default_dtype; float64 only on explicit request.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from .core.dtype import (  # noqa: F401,E402
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, finfo, iinfo, dtype_name,
)
from .core.tensor import SelectedRows, Tensor, to_tensor, is_tensor  # noqa: F401,E402
from .core import autograd as _autograd_core  # noqa: E402
from .core.autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401,E402
from .core.autograd import grad  # noqa: F401,E402

from .ops import *  # noqa: F401,F403,E402
from .ops import methods as _methods  # noqa: E402
from .ops import dispatch  # noqa: F401,E402

_methods.patch_tensor_methods()

from .framework.random import seed, get_rng_state, set_rng_state  # noqa: F401,E402
from .framework import save, load  # noqa: F401,E402

from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from .hapi.summary import flops, summary  # noqa: F401,E402
from .utils.flags import get_flags, set_flags  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import geometric  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import serving  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import tensor  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import strings  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import version  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from .compat import (  # noqa: F401,E402
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace, CustomPlace, shape,
    tolist, reverse, batch, set_printoptions, disable_signal_handler,
    check_shape, set_cuda_rng_state, get_cuda_rng_state)
from .compat import _export_inplace as _exp_inp  # noqa: E402
_exp_inp(globals())
del _exp_inp

# remaining reference top-level aliases
from .nn.utils_ import ParamAttr  # noqa: F401,E402
bool = bool_  # noqa: F401,E402  (paddle.bool dtype alias, like reference)
import numpy as _np  # noqa: E402
dtype = _np.dtype  # Tensor.dtype values are numpy dtype instances, so
# isinstance(x.dtype, paddle.dtype) holds — the reference idiom
floor_mod = mod  # noqa: F811,E402
floor_mod_ = globals().get("mod_", None) or floor_mod


class LazyGuard:
    """Parity: paddle.LazyGuard — defers parameter initialization in the
    reference; initialization here is already lazy-cheap (jax arrays
    materialize on first use), so the guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
from .ops import linalg  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402

from .nn.layer.layers import Layer  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .distributed.parallel import DataParallel  # noqa: F401,E402


def disable_static(place=None):
    """No-op: paddle_tpu is always in eager (dygraph) mode; compiled execution
    is opt-in via paddle_tpu.jit.to_static. Kept for API parity."""


def enable_static():
    raise RuntimeError(
        "paddle_tpu has no separate static-graph mode: use "
        "paddle_tpu.jit.to_static(fn) to get compiled (XLA) execution.")


def in_dynamic_mode():
    return True
