"""paddle.regularizer — L1Decay / L2Decay.

Parity: reference `python/paddle/regularizer.py`: regularizer objects
passed as `weight_decay=` to optimizers (or per-param via ParamAttr);
L2Decay folds into the gradient (coupled decay), L1Decay adds
coeff * sign(w).
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __float__(self):
        return self._coeff

    def apply(self, param_array, grad_array):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    """grad += coeff * sign(w) (parity: regularizer.py L1Decay)."""

    def apply(self, param_array, grad_array):
        import jax.numpy as jnp
        return grad_array + self._coeff * jnp.sign(
            param_array.astype(grad_array.dtype))


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * w (parity: regularizer.py L2Decay)."""

    def apply(self, param_array, grad_array):
        return grad_array + self._coeff * param_array.astype(grad_array.dtype)
