"""paddle.hub — load models from a hubconf.py.

Parity: reference `python/paddle/hub.py` (list/help/load over github /
gitee / local sources). This build supports the `local` source (a
directory containing `hubconf.py`); remote sources raise — the sandbox
has no egress, and the reference's entrypoint protocol (callables in
hubconf, `dependencies` list) is fully honored for local dirs.
"""
from __future__ import annotations

import importlib.util
import os

__all__ = ["list", "help", "load"]

_HUBCONF_CACHE = {}


def _load_hubconf(repo_dir, force_reload=False):
    path = os.path.join(repo_dir, "hubconf.py")
    key = os.path.abspath(path)
    if not force_reload and key in _HUBCONF_CACHE:
        return _HUBCONF_CACHE[key]
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    deps = getattr(mod, "dependencies", None)
    if deps:
        import importlib as _il
        for d in deps:
            try:
                _il.import_module(d)
            except ImportError as e:
                raise RuntimeError(f"hub dependency {d!r} missing") from e
    _HUBCONF_CACHE[key] = mod
    return mod


def _check_source(source):
    if source != "local":
        raise NotImplementedError(
            "paddle_tpu.hub supports source='local' only (no network "
            "egress); point repo_dir at a directory with hubconf.py")


def list(repo_dir, source="local", force_reload=False):
    """Entrypoint names exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    """Docstring of one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hubconf has no entrypoint {model!r}")
    return fn.__doc__


def load(repo_dir, model, *args, source="local", force_reload=False,
         **kwargs):
    """Instantiate one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hubconf has no entrypoint {model!r}")
    return fn(*args, **kwargs)
