from . import dtype, tensor, autograd  # noqa: F401
