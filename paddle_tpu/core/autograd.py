"""Eager reverse-mode autograd for paddle_tpu.

Capability parity with the reference's eager autograd
(`/root/reference/paddle/fluid/eager/grad_node_info.h`, `backward.cc:105`):
a tape of grad nodes walked in reverse topological order with per-edge
gradient accumulation.

TPU-native design: instead of hand-written per-op grad kernels, every op's
backward is obtained from `jax.vjp` at call time. Because the tape is plain
Python driving jax operations, the SAME code path works:
  * eagerly on concrete `jax.Array`s (dygraph mode), and
  * under `jax.jit` tracing (to_static mode) — the tape unrolls into the
    traced computation, producing one fused XLA program for fwd+bwd.
This replaces the reference's dual eager/static autograd engines with one
mechanism, which is the idiomatic JAX formulation.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "backward",
]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class _GradModeCtx:
    """Context manager / decorator toggling grad recording."""

    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, fn):
        if not callable(fn):
            raise TypeError("no_grad/enable_grad used as decorator needs a callable")
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradModeCtx(self._mode):
                return fn(*args, **kwargs)

        return wrapper


def no_grad(fn=None):
    """Disable gradient recording (context manager or decorator).

    Parity: `paddle.no_grad` (reference python/paddle/base/dygraph/base.py).
    """
    ctx = _GradModeCtx(False)
    return ctx(fn) if fn is not None else ctx


def enable_grad(fn=None):
    ctx = _GradModeCtx(True)
    return ctx(fn) if fn is not None else ctx


class GradNode:
    """One recorded op on the tape.

    Holds the `jax.vjp`-produced pullback, references to the input Tensors
    (edges of the autograd graph), and accumulation buffers for the
    cotangents of each output.

    Parity: `egr::GradNodeBase` + `Edge` (reference
    fluid/eager/grad_node_info.h:197,53) and `GradTensorHolder`
    accumulation (fluid/eager/grad_tensor_holder.h).
    """

    __slots__ = (
        "name",
        "vjp_fn",
        "fwd_closed",
        "inputs",
        "out_avals",
        "out_treedef",
        "out_cots",
        "n_outputs",
        "_released",
    )

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence[Any],
                 out_avals: List[jax.ShapeDtypeStruct], out_treedef=None,
                 fwd_closed: Optional[Callable] = None):
        self.name = name
        self.vjp_fn = vjp_fn
        # array-level forward closure — re-differentiated for create_graph
        # (the saved pullback hides the primal dependence)
        self.fwd_closed = fwd_closed
        self.inputs = list(inputs)  # Tensors
        self.out_avals = out_avals
        self.out_treedef = out_treedef
        self.n_outputs = len(out_avals)
        self.out_cots: List[Optional[jax.Array]] = [None] * self.n_outputs
        self._released = False

    def accumulate(self, idx: int, cot):
        if self.out_cots[idx] is None:
            self.out_cots[idx] = cot
        else:
            self.out_cots[idx] = self.out_cots[idx] + cot

    def release(self):
        self.vjp_fn = None
        self.fwd_closed = None
        self.inputs = []
        self.out_cots = [None] * self.n_outputs
        self._released = True


def _topo_order(root_nodes: Sequence[GradNode]) -> List[GradNode]:
    """Reverse-topological order over the tape graph reachable from roots."""
    order: List[GradNode] = []
    visited = set()
    # Iterative DFS with post-ordering (graph can be deep for big models).
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            parent = getattr(t, "_grad_node", None)
            if parent is not None and id(parent) not in visited:
                stack.append((parent, False))
    order.reverse()  # roots first, leaves last
    return order


def _vjp_on_tape(node, cots):
    """Recompute this op's vjp THROUGH the dispatch funnel so the gradient
    computation is itself a taped op over (primal inputs, cotangents) —
    the create_graph path. Primals are read from the node's input Tensors
    (in-place-updated primals follow PyTorch-style staleness semantics)."""
    from ..ops.dispatch import apply_op

    n_in = len(node.inputs)
    treedef = node.out_treedef
    n_out = node.n_outputs
    fwd = node.fwd_closed

    def dbl(*arrs):
        prim = arrs[:n_in]
        cot = list(arrs[n_in:])
        _, pull = jax.vjp(fwd, *prim)
        if treedef is not None:
            ct = jax.tree_util.tree_unflatten(treedef, cot)
        else:
            ct = cot[0] if n_out == 1 else tuple(cot)
        return tuple(pull(ct))

    return apply_op(node.name + "_grad", dbl, *node.inputs, *cots)


def _zero_cotangent(aval):
    """Zero cotangent for an unused output; float0 for non-inexact outputs
    (e.g. the indices output of topk), matching jax.vjp's expectations."""
    import numpy as np
    if jnp.issubdtype(aval.dtype, jnp.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, dtype=jax.dtypes.float0)


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             _capture: Optional[dict] = None, create_graph: bool = False):
    """Run reverse-mode accumulation from `tensors` into leaf `.grad`s.

    Parity: `egr::RunBackward` (reference fluid/eager/backward.cc:105):
    seed root cotangents, walk nodes in reverse-topo order, invoke each
    node's pullback, scatter cotangents along edges, accumulate into leaf
    grads at `GradNodeAccumulation` (here: Tensor.grad on leaves).

    create_graph=True routes every pullback through the dispatch funnel as
    a re-differentiated op over (primal inputs, cotangents) — so the
    gradient computation itself lands on the tape and `grad()` composes to
    higher orders (parity: GeneralGrad + create_graph,
    fluid/eager/backward.cc:103). Uses the node's saved forward closure;
    the jax.vjp pullback alone hides the primal dependence.
    """
    from .tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    hooked_slots = {}      # (id(node), out_idx) -> hooks: applied once on
    hooked_leaves = {}     # id(t) -> (t, partial sum): the ACCUMULATED
                           # cotangent (paddle hook semantics), not per edge

    def _run_hooks(hooks, g):
        for h in list(hooks):
            out = h(g if isinstance(g, Tensor)
                    else Tensor(g, stop_gradient=True))
            if out is not None:
                g = out if (create_graph and isinstance(out, Tensor)) else (
                    out._data if isinstance(out, Tensor)
                    else jnp.asarray(out))
        return g

    def _scatter(t, g):
        hooks = getattr(t, "_grad_hooks", None)
        if hooks:
            if t._grad_node is not None:
                hooked_slots[(id(t._grad_node), t._grad_out_idx)] = hooks
            else:
                tid = id(t)
                prev = hooked_leaves.get(tid)
                acc = g if prev is None else prev[1] + g
                hooked_leaves[tid] = (t, acc)
                return          # deposited (transformed) after the walk
        if _capture is not None and id(t) in _capture:
            prev = _capture[id(t)]
            _capture[id(t)] = g if prev is None else prev + g
        if t.stop_gradient:
            return
        parent = t._grad_node
        if parent is None:
            # Under grad() (capture mode) leaf .grad must stay untouched.
            if _capture is None:
                t._accumulate_grad(g)
        else:
            parent.accumulate(t._grad_out_idx, g)

    root_nodes = []
    for t, g in zip(tensors, grad_tensors):
        node = t._grad_node
        if node is None:
            if not t.stop_gradient or (_capture is not None and id(t) in _capture):
                # Leaf used as root: grad of itself w.r.t. itself.
                seed = g.data if isinstance(g, Tensor) else (
                    jnp.asarray(g) if g is not None else jnp.ones(t.shape, t.dtype))
                _scatter(t, seed)
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires grad_tensors "
                    f"(got shape {t.shape})")
            seed = jnp.ones(t.shape, t.dtype)
        elif isinstance(g, Tensor):
            # keep the Tensor (with its graph) under create_graph so
            # d(grad)/d(grad_outputs) chains through
            seed = g if create_graph else g.data
        else:
            seed = jnp.asarray(g)
        node.accumulate(t._grad_out_idx, seed)
        root_nodes.append(node)

    for node in _topo_order(root_nodes):
        if node._released:
            raise RuntimeError(
                f"Trying to backward through node {node.name} a second time "
                "(set retain_graph=True to allow this).")
        if all(c is None for c in node.out_cots):
            continue
        cots = [
            c if c is not None else _zero_cotangent(av)
            for c, av in zip(node.out_cots, node.out_avals)
        ]
        for i in range(len(cots)):
            hk = hooked_slots.pop((id(node), i), None)
            if hk is not None:
                cots[i] = _run_hooks(hk, cots[i])
        if create_graph and node.fwd_closed is not None:
            in_grads = _vjp_on_tape(node, cots)
        elif node.out_treedef is not None:
            in_grads = node.vjp_fn(jax.tree_util.tree_unflatten(node.out_treedef, cots))
        else:
            in_grads = node.vjp_fn(cots[0] if node.n_outputs == 1 else tuple(cots))
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            # float0 tangents come back for integer/bool inputs — skip.
            if hasattr(g, "dtype") and g.dtype == jax.dtypes.float0:
                continue
            _scatter(t, g)
        if not retain_graph:
            node.release()
        else:
            node.out_cots = [None] * node.n_outputs

    for t, total in hooked_leaves.values():
        g = _run_hooks(t._grad_hooks, total)
        if isinstance(g, Tensor):
            g = g.data
        if _capture is not None and id(t) in _capture:
            prev = _capture[id(t)]
            _capture[id(t)] = g if prev is None else prev + g
        if not t.stop_gradient and _capture is None:
            t._accumulate_grad(g)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """`paddle.grad` analog: gradients of outputs w.r.t. an explicit input list.

    Parity: `egr::GeneralGrad` (reference fluid/eager/backward.cc:103,436).
    Implemented by running the tape walk with accumulation redirected into a
    side table rather than leaf `.grad`s.
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    # Redirect accumulation into a side table so .grad is untouched.
    capture = {id(t): None for t in inputs}
    retain = True if (retain_graph is None or create_graph) else retain_graph
    backward(outputs, grad_outputs, retain_graph=retain, _capture=capture,
             create_graph=create_graph)

    results = []
    for i, t in enumerate(inputs):
        g = capture[id(t)]
        if g is None and not allow_unused:
            raise RuntimeError(
                f"Input {i} is unreachable from outputs "
                "(pass allow_unused=True to return None).")
        if g is None:
            results.append(None)
        elif isinstance(g, Tensor):
            # create_graph: the grad carries its own tape for higher orders
            results.append(g)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results
