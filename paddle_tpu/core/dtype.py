"""Dtype system for paddle_tpu.

Capability parity with the reference's dtype surface
(`/root/reference/paddle/phi/common/data_type.h`, `float16.h`, `bfloat16.h`):
paddle-style dtype names mapped onto JAX/numpy dtypes. TPU-first: bfloat16 is
the preferred half precision; float64 is supported but discouraged (XLA on TPU
emulates it slowly).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (these ARE numpy/jax dtypes so they interop freely).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # paddle aliases
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize a user-provided dtype (str | np dtype | jnp dtype) to jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return jnp.dtype(_NAME_TO_DTYPE[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    """Paddle-style name of a dtype."""
    d = jnp.dtype(dtype)
    return d.name


def is_floating_point(dtype) -> bool:
    d = jnp.dtype(convert_dtype(dtype))
    return jnp.issubdtype(d, jnp.floating)


def is_integer(dtype) -> bool:
    d = jnp.dtype(convert_dtype(dtype))
    return jnp.issubdtype(d, jnp.integer)


def is_complex(dtype) -> bool:
    d = jnp.dtype(convert_dtype(dtype))
    return jnp.issubdtype(d, jnp.complexfloating)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(np.dtype(convert_dtype(dtype)))


# Default dtype management (reference: python/paddle/base/framework.py
# get_default_dtype/set_default_dtype).
_default_dtype = [jnp.float32]


def set_default_dtype(dtype):
    d = convert_dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype[0] = d


def get_default_dtype():
    return _default_dtype[0]
