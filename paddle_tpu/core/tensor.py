"""The paddle_tpu Tensor: an eager, autograd-tracking façade over jax.Array.

Capability parity with the reference's `paddle.Tensor`
(`/root/reference/paddle/phi/api/include/tensor.h:82` +
`paddle/fluid/pybind/eager.cc` python object): shape/dtype/place accessors,
numpy interop, rich operators, `.backward()`, `.grad`, `.stop_gradient`.

TPU-native design notes:
  * The payload is always a `jax.Array` (or a jax tracer when the enclosing
    code is being traced by `jax.jit` — Tensor is registered as a pytree so
    Tensor-level programs compile to single XLA executables).
  * There is no Place/stream plumbing: device residency is carried by the
    jax.Array's sharding; `to()`/`cuda()` analogs map to `jax.device_put`.
  * Mutation (`copy_`, in-place ops, `__setitem__`) rebinds the wrapped
    functional array, which matches XLA's value semantics while preserving
    the reference's in-place API surface.
"""
from __future__ import annotations

import operator
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .dtype import convert_dtype, get_default_dtype

__all__ = ["Tensor", "to_tensor", "is_tensor"]


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad_buffer",
        "_grad_node",
        "_grad_out_idx",
        "name",
        "_is_param",
        # distributed metadata (DistTensor-analog view, see distributed/)
        "process_mesh",
        "placements",
        "_spec",
        "_spmd_spec",  # placement inferred by the SPMD rule registry
                       # (auto_parallel/propagation.py)
        "_lr_scale",
        "_asp_mask",   # incubate.asp 2:4 sparsity mask (travels with the
                       # parameter through deepcopy, unlike an id registry)
        "_grad_hooks",  # register_hook callbacks run on the cotangent
        "__weakref__",
    )

    def __init__(self, data, stop_gradient: bool = True, name: str = ""):
        if isinstance(data, Tensor):
            data = data._data
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad_buffer = None
        self._grad_node = None
        self._grad_out_idx = 0
        self.name = name
        self._is_param = False

    # ------------------------------------------------------------------ data
    @property
    def data(self):
        """The underlying jax.Array."""
        return self._data

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self):
        try:
            devs = self._data.devices()
            return next(iter(devs))
        except Exception:
            return None

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    def __jax_array__(self):
        # jnp.asarray(Tensor) consults this before __array__; without it
        # older jax rejects Tensors outright (newer jax accepts them via
        # the numpy protocol, but returns a host copy — this keeps the
        # device array and works on both)
        return self._data

    def item(self, *args):
        return np.asarray(self._data).item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_str},\n       {np.asarray(jax.device_get(self._data))!r})")

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of a multi-element Tensor is ambiguous")
        return bool(np.asarray(self._data))

    def __int__(self):
        return int(np.asarray(self._data))

    def __float__(self):
        return float(np.asarray(self._data))

    def __index__(self):
        return operator.index(np.asarray(self._data).item())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -------------------------------------------------------------- autograd
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad_buffer is None:
            return None
        return Tensor(self._grad_buffer, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad_buffer = None
        else:
            self._grad_buffer = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    def _accumulate_grad(self, g):
        if g.dtype != self.dtype:
            g = g.astype(self.dtype)
        if self._grad_buffer is None:
            self._grad_buffer = g
        else:
            self._grad_buffer = self._grad_buffer + g

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad_buffer = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops.dispatch import apply_op
        return apply_op("clone", lambda x: x, self)

    @property
    def requires_grad(self):
        return not self.stop_gradient

    @requires_grad.setter
    def requires_grad(self, v):
        self.stop_gradient = not v

    # ------------------------------------------------------------- mutation
    def copy_(self, value, *a):
        """In-place copy (rebind). Breaks the autograd link like the reference's
        inplace-on-leaf check would demand outside of no_grad."""
        v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        self._data = v.astype(self.dtype) if v.dtype != self.dtype else v
        return self

    def set_value(self, value):
        return self.copy_(value)

    def fill_(self, value):
        self._data = jnp.full(self._data.shape, value, self._data.dtype)
        return self

    def zero_(self):
        return self.fill_(0)

    def _replace_data(self, new_data):
        """Internal: rebind payload preserving autograd metadata (optimizer use)."""
        self._data = new_data
        return self

    # ------------------------------------------------------------ conversion
    def astype(self, dtype) -> "Tensor":
        from ..ops.dispatch import apply_op
        d = convert_dtype(dtype)
        return apply_op("cast", lambda x: x.astype(d), self)

    cast = astype

    def to(self, *args, **kwargs):
        # Accept .to(dtype), .to(device_str) loosely.
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu"):
                continue  # single-process device residency is jax-managed
            else:
                try:
                    out = out.astype(a)
                except Exception:
                    pass
        return out

    def cpu(self):
        return Tensor(jax.device_get(self._data), self.stop_gradient, self.name)

    def cuda(self, device_id=None, blocking=True):
        """API parity: moves to the accelerator — jax already placed the
        array on the default device, so this is the identity."""
        return self

    def pin_memory(self):
        return self

    # ------------------------------------------------------ hooks/compat
    def register_hook(self, hook):
        """Run `hook(grad)` when this tensor's gradient is produced during
        backward; a non-None return replaces the gradient (parity:
        Tensor.register_hook / egr GradNode hooks)."""
        if self.stop_gradient:
            raise ValueError(
                "cannot register_hook on a tensor with stop_gradient=True "
                "(no gradient will ever be produced for it)")
        hooks = getattr(self, "_grad_hooks", None)
        if hooks is None:
            hooks = []
            self._grad_hooks = hooks
        hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in hooks:
                    hooks.remove(hook)
        return _Handle()

    def ndimension(self):
        return len(self._data.shape)

    def element_size(self):
        return int(np.dtype(self._data.dtype).itemsize)

    def get_tensor(self):
        """Legacy LoDTensor accessor — the Tensor IS its storage here."""
        return self

    def value(self):
        return self

    @property
    def persistable(self):
        return bool(getattr(self, "_is_param", False))

    @persistable.setter
    def persistable(self, v):
        self._is_param = bool(v)

    @property
    def type(self):
        return "lod_tensor"

    @property
    def strides(self):
        sh = self._data.shape
        st, acc = [], 1
        for s in reversed(sh):
            st.append(acc)
            acc *= int(s)
        return list(reversed(st))

    def data_ptr(self):
        return id(self._data)

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """Parity: `paddle.to_tensor` (reference python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        arr = data._data
    elif isinstance(data, (jax.Array, np.ndarray)):
        arr = jnp.asarray(data)
    else:
        np_arr = np.asarray(data)
        if np_arr.dtype == np.float64 and dtype is None:
            np_arr = np_arr.astype(np.dtype(get_default_dtype()))
        arr = jnp.asarray(np_arr)
    if dtype is not None:
        d = convert_dtype(dtype)
        if arr.dtype != d:
            arr = arr.astype(d)
    return Tensor(arr, stop_gradient=stop_gradient)


# --------------------------------------------------------------------- pytree
def _tensor_flatten(t: Tensor):
    return (t._data,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    t = Tensor(children[0], stop_gradient=aux[0], name=aux[1])
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class SelectedRows:
    """Sparse row-slice tensor (parity: `phi::SelectedRows`,
    `paddle/phi/core/selected_rows.h`): a (rows, value) pair representing a
    tall tensor in which only `rows` hold data — the reference's embedding-
    gradient format. On TPU dense scatter-add is the fast path, so this
    type is an interchange/API surface: `to_dense()` materializes, and
    embedding-style lookups can build one cheaply."""

    def __init__(self, rows, value, height):
        import jax.numpy as jnp
        self.rows = jnp.asarray(rows._data if isinstance(rows, Tensor)
                                else rows)
        self.value = value if isinstance(value, Tensor) else Tensor(value)
        self.height = int(height)

    @property
    def shape(self):
        return [self.height] + list(self.value.shape[1:])

    def to_dense(self):
        import jax.numpy as jnp
        v = self.value._data
        out = jnp.zeros((self.height,) + tuple(v.shape[1:]), v.dtype)
        return Tensor(out.at[self.rows].add(v))

    def merge_rows(self):
        """Coalesce duplicate rows (parity: scatter::MergeAdd)."""
        import jax.numpy as jnp
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0], fill_value=-1)
        v = self.value._data
        merged = jnp.zeros((uniq.shape[0],) + tuple(v.shape[1:]), v.dtype)
        merged = merged.at[inv].add(v)
        keep = uniq >= 0
        return SelectedRows(uniq[keep], Tensor(merged[keep]), self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"n_rows={self.rows.shape[0]})")
