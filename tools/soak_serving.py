"""Randomized fault-injection soak for the serving engine (ISSUE 3 + 5).

Runs the SAME seeded mixed workload four times on CPU — plain-decode
clean and chaos, then SPECULATIVE-decode (NgramProposer, K=4) clean and
chaos — and asserts the resilience acceptance criteria on each pair:

* zero engine crashes (injected transients can never exhaust the retry
  budget by construction: times <= max_retries);
* every KV page reclaimed and allocator/radix ref-counts consistent at
  drain;
* greedy outputs of UNAFFECTED requests bit-identical to the clean run
  (affected = quarantined / expired / aborted / shed);
* every fault point ARMED IN THAT PASS actually fired (a soak that
  injected nothing proves nothing);
* spec-decode extras (ISSUE 5): the spec-clean pass emits streams
  bit-identical to the plain clean pass (speculation only changes how
  many launches, never which tokens) with acceptance > 0, and the
  spec-chaos pass layers a draft-mismatch STORM (garbage drafts — all
  rejected, output-invariant by the acceptance rule), injected
  rollback-OOM during draft extension, and NaN in verify logits on top
  of the ISSUE-3 chaos;
* int8-KV extras (ISSUE 6): the same workload runs a clean + chaos
  pair under kv_dtype="int8" (quantized pages + per-slot scales) —
  unaffected requests must stay bit-identical WITHIN the int8 pair
  (quantize-on-write is deterministic, so chaos may only change
  affected requests, exactly like the full-precision pair), and every
  page/refcount reclamation check holds on the quantized pool.

Deterministic end to end: workload, fault schedule, aborts and the
deadline clock all derive from --seed; wall-clock never enters the
engine (FakeClock + storm skew only). Bounded runtime: the engine's own
drain guard plus a hard step ceiling.

* tiered-KV extras (ISSUE 17, `--spill`): a spill-pressure workload
  (six shared prefixes thrashing a shrunken device pool) runs three
  ways — host tier off, on, and on with every `host_spill.*` read
  fault armed. The tier must be token-invisible both times (spill
  on == off for EVERY request; faults degrade to recompute with NO
  affected requests), both pools must reclaim to zero at drain, every
  armed fault point must fire, and the clean spill pass must serve
  MORE cached tokens than the HBM-only ceiling at the same device
  pool (the perf_opt acceptance).

* multi-LoRA extras (ISSUE 15, `--lora`): the workload spread over 3
  resident adapters + base rows runs a clean/chaos pair — a 4th "hot"
  adapter's MID-STREAM load fails typed under chaos (its tail of the
  workload sheds `AdapterNotLoaded` at the door, never serves wrong
  weights), the `serving.lora.evict_race` guard refuses evicting a
  pinned adapter, and every co-batched row of the OTHER adapters stays
  bit-identical to the clean lora pass.

Usage:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
            python tools/soak_serving.py [--requests 200] [--seed 0]
(or `make soak`; --no-spec skips the two spec passes, --lora adds the
multi-LoRA pair, --spill the tiered-KV triple). Exits 0 on
success, 1 with a report on violation — this is a test harness, not
bench.py; it is allowed to fail loudly.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# CPU pin BEFORE jax initializes (the hosting image's sitecustomize
# force-registers a TPU platform; mirror tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                                   # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np                                           # noqa: E402

import paddle_tpu as paddle                                  # noqa: E402
from paddle_tpu.models.llama import (LlamaConfig,            # noqa: E402
                                     LlamaForCausalLM)
from paddle_tpu.serving import (EngineOverloaded,            # noqa: E402
                                NgramProposer, RetryPolicy,
                                ServingEngine, TransientDeviceError)
from paddle_tpu.utils import faults                          # noqa: E402

# single-bucket grid: every run hits identical program shapes, so the
# bit-identity comparison is exact (SERVING.md determinism contract).
# The spec passes pin a single K bucket too — a chaos-perturbed draft
# length then changes dl DATA, never the verify program shape.
ENGINE_KW = dict(num_pages=40, page_size=8, token_budget=48,
                 batch_buckets=[8], prefill_buckets=[32], pages_buckets=[8],
                 temperature=0.0, max_queue_len=32)
SPEC_KW = dict(spec_k=4, spec_buckets=[4])
TTL_S = 1000.0          # generous; only storm skew can expire anything
ABORT_FRACTION = 0.04
MAX_STEPS_FACTOR = 400  # hard ceiling: steps <= factor * num_requests


class FakeClock:
    """Engine deadline clock: advances a fixed tick per call, so expiry
    is a function of step count + injected storm skew, never host
    wall-clock."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1e-3
        return self.t


def make_workload(n, seed):
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, 128, (16,)).tolist()    # 2 full pages
    work = []
    for i in range(n):
        u = rng.random()
        if u < 0.3:                                 # radix exercise
            p = shared + rng.randint(0, 128, (rng.randint(2, 8),)).tolist()
        elif u < 0.55:                              # ngram exercise:
            cyc = rng.randint(0, 128, (rng.randint(2, 4),)).tolist()
            p = (cyc * 10)[:rng.randint(8, 24)]     # repetitive prompt
        else:
            p = rng.randint(0, 128, (rng.randint(4, 24),)).tolist()
        work.append((p, int(rng.randint(3, 10))))
    return work


def make_spill_workload(n, seed):
    """Spill-pressure variant (ISSUE 17): six shared 24-token (3-page)
    prefixes revisited round-robin with short random tails. The spill
    passes' shrunken device pool cannot hold all six prefixes plus the
    running tails at once, so the revisits force demote -> host ->
    promote cycles on every lap — exactly the traffic the host tier
    exists for, and a steady stream of store reads for the armed
    host_spill.* specs to hit."""
    rng = np.random.RandomState(seed + 17)
    prefixes = [rng.randint(0, 128, (24,)).tolist() for _ in range(6)]
    work = []
    for i in range(n):
        p = prefixes[i % len(prefixes)] + \
            rng.randint(0, 128, (rng.randint(2, 8),)).tolist()
        work.append((p, int(rng.randint(3, 8))))
    return work


def run_workload(model, work, *, chaos, seed, report, spec=False,
                 kv_dtype=None, trace=None, label=None, keep=None,
                 extra_kw=None, spill_chaos=False):
    """One full soak pass; returns ({idx: tokens}, affected_idx_set).
    `trace` (a RequestTracer) turns per-request tracing on for the
    pass (ISSUE 10 — the overhead measurement and the exported trace
    the `make soak` trace-report smoke reads); `keep` (a dict) receives
    the engine's flight-recorder timeline + Prometheus exposition
    before shutdown so the final report prints through the
    observability paths instead of an ad-hoc dict dump. `extra_kw`
    overrides engine kwargs (the spill passes shrink the device pool
    and attach the host tier); `spill_chaos` arms the three
    `host_spill.*` read-path faults INSTEAD of the engine chaos set —
    they must degrade to recompute with NO affected requests, so they
    get their own switch rather than riding `chaos`."""
    rng = np.random.RandomState(seed + 1)
    abort_at = {i for i in range(len(work))
                if rng.random() < ABORT_FRACTION} if chaos else set()

    kw = dict(ENGINE_KW, kv_dtype=kv_dtype)
    if extra_kw:
        kw.update(extra_kw)
    if spec:
        kw.update(SPEC_KW, proposer=NgramProposer())
    eng = ServingEngine(
        model, clock=FakeClock(), default_ttl_s=TTL_S,
        retry_policy=RetryPolicy(max_retries=12, base_s=0.0,
                                 sleep=lambda s: None),
        trace=trace, **kw)
    armed = set()

    def arm(name, **kwargs):
        faults.inject(name, **kwargs)
        armed.add(name)

    if chaos and spec:
        # ISSUE 5 chaos: draft-mismatch storm (garbage drafts — the
        # acceptance rule makes them output-invariant), rollback-OOM
        # during draft extension (the alloc point fires inside
        # append_token there too), NaN in verify logits, transient
        # verify-step exceptions. decode_step is NOT armed: the spec
        # engine replaces the decode launch with verify.
        arm("serving.spec.draft_storm", payload=True, after=2, times=2)
        arm("serving.spec.draft_storm", payload=True, prob=0.05,
            times=10, seed=seed + 9)
        arm("serving.engine.verify_step",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            after=4, times=1)
        arm("serving.engine.verify_step",
            exc=TransientDeviceError("soak: relay loss"),
            prob=0.03, times=9, seed=seed + 10)
    if chaos:
        # Every point gets one DETERMINISTIC early spec (the "every
        # armed point fired" assertion must not ride on a seeded coin)
        # plus a seeded probabilistic spec for spread. Transient totals
        # stay < max_retries(12), so retry exhaustion (and thus
        # EngineFailure) is impossible by construction.
        arm("serving.engine.prefill_chunk",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            after=3, times=1)
        arm("serving.engine.prefill_chunk",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            prob=0.03, times=9, seed=seed + 2)
        if not spec:
            arm("serving.engine.decode_step",
                exc=TransientDeviceError("soak: relay loss"),
                after=4, times=1)
            arm("serving.engine.decode_step",
                exc=TransientDeviceError("soak: relay loss"),
                prob=0.03, times=9, seed=seed + 3)
        arm("serving.kv.alloc_page", payload=True,
            after=5, times=2)
        arm("serving.kv.alloc_page", payload=True,
            prob=0.05, times=20, seed=seed + 4)
        nan_rng = np.random.RandomState(seed + 5)
        arm("serving.engine.nan_logits",
            payload=lambda reqs: [nan_rng.randint(len(reqs))],
            after=6, times=1)
        arm("serving.engine.nan_logits",
            payload=lambda reqs: [nan_rng.randint(len(reqs))],
            prob=0.02, times=3, seed=seed + 6)
        # the storm fires at boundary hits 11-12, whose combined 1200 s
        # of skew blows every pre-storm deadline (TTL 1000) — a burst
        # expiry wave mid-traffic
        arm("serving.engine.deadline_storm", payload=600.0,
            after=10, times=2)
        arm("serving.radix.insert",
            exc=RuntimeError("soak: donation failed"),
            after=2, times=1)
        arm("serving.radix.insert",
            exc=RuntimeError("soak: donation failed"),
            prob=0.05, times=7, seed=seed + 8)
    if spill_chaos:
        # ISSUE 17 chaos: every host-tier read-path fault. corrupt =
        # CRC reject at decode (node dropped, recompute); slow =
        # deadline miss (node kept on host, recompute now, retry
        # later); lost = backing buffer gone (slot forgotten under its
        # holders, node dropped, recompute). One deterministic early
        # spec per point + a seeded coin for spread, same convention
        # as the engine chaos set.
        arm("host_spill.corrupt", payload=True, after=1, times=1)
        arm("host_spill.corrupt", payload=True,
            prob=0.04, times=6, seed=seed + 11)
        arm("host_spill.slow", payload=True, after=3, times=1)
        arm("host_spill.slow", payload=True,
            prob=0.04, times=6, seed=seed + 12)
        arm("host_spill.lost", payload=True, after=5, times=1)
        arm("host_spill.lost", payload=True,
            prob=0.03, times=4, seed=seed + 13)

    idx_of = {}
    pending = list(enumerate(work))
    sheds = 0
    steps = 0
    max_steps = MAX_STEPS_FACTOR * max(1, len(work))
    out = {}
    try:
        while pending or eng.has_work():
            # arrival waves: up to 4 per step; shed -> retry next step
            admitted_this_step = 0
            while pending and admitted_this_step < 4:
                i, (p, m) = pending[0]
                try:
                    rid = eng.add_request(p, max_new_tokens=m)
                except EngineOverloaded:
                    sheds += 1
                    break
                idx_of[rid] = i
                pending.pop(0)
                admitted_this_step += 1
            for rid, tok in eng.step():
                i = idx_of[rid]
                out.setdefault(i, []).append(tok)
                if i in abort_at and len(out[i]) == 1:
                    eng.abort(rid)
            steps += 1
            if steps > max_steps:
                raise AssertionError(
                    f"soak failed to drain after {steps} steps")

        affected = set()
        reasons = {}
        for rid, i in idx_of.items():
            req = eng.requests.get(rid)
            assert req is not None, f"request {rid} evicted mid-soak"
            reasons[req.finish_reason] = reasons.get(
                req.finish_reason, 0) + 1
            if req.finish_reason in ("quarantined", "expired", "abort"):
                affected.add(i)
            out[i] = list(req.output_ids)

        # ---- reclamation + ref-count consistency at drain -----------
        if eng.radix is not None:
            eng.radix.check_invariants()
            assert eng.allocator.num_used == eng.radix.num_cached_pages
        eng.reset_prefix_cache()
        assert eng.allocator.num_used == 0, "KV pages leaked"
        eng.allocator.check_invariants()
        if getattr(eng, "host_store", None) is not None:
            # BOTH pools must come back empty (ISSUE 17 reclamation):
            # radix.clear() released every host tree ref too
            assert eng.host_store.num_used == 0, "host pages leaked"
            eng.host_store.check_invariants()

        snap = eng.metrics.snapshot()
        if label is None:
            label = ("int8_" if kv_dtype == "int8" else "") \
                + ("spec_" if spec else "") \
                + ("chaos" if chaos else "clean")
        rep = {
            "steps": steps, "sheds": sheds,
            "finish_reasons": reasons,
            "affected": len(affected),
            "preemptions": snap["requests_preempted"],
            "step_retries": snap["step_retries"],
            "quarantined": snap["requests_quarantined"],
            "expired": snap["deadline_expired"],
            "aborted": snap["requests_aborted"],
            "prefix_hits": snap["prefix_hits"],
        }
        if spec:
            rep.update({
                "spec_steps": snap["spec_steps"],
                "spec_drafted": snap["spec_drafted_tokens"],
                "spec_accepted": snap["spec_accepted_tokens"],
                "spec_rollback": snap["spec_rollback_tokens"],
                "spec_oom_drops": snap["spec_draft_oom_drops"],
                "spec_tokens_per_step": snap.get("spec_tokens_per_step"),
            })
        if getattr(eng, "host_store", None) is not None:
            rep.update({
                "cached_tokens": snap["cached_tokens_served"],
                "kv_pages_demoted": snap["kv_pages_demoted"],
                "kv_pages_promoted": snap["kv_pages_promoted"],
                "host_prefix_hits": snap["host_prefix_hits"],
                "host_pages_dropped": snap["host_pages_dropped"],
                "spill_faults": [snap["host_spill_corrupt"],
                                 snap["host_spill_slow"],
                                 snap["host_spill_lost"]],
            })
        elif extra_kw is not None:
            rep["cached_tokens"] = snap["cached_tokens_served"]
        report[label] = rep
        if chaos or spill_chaos:
            fired = faults.fired_counts()
            report[f"fired_{label}"] = fired
            for pt in sorted(armed):
                assert fired.get(pt, 0) >= 1, \
                    f"armed fault point {pt} never fired"
        if keep is not None:
            keep["timeline"] = eng.timeline()
            keep["prometheus"] = eng.metrics.prometheus_text()
        return out, affected
    finally:
        faults.clear()
        faults.reset_counts()
        eng.shutdown()


def run_lora_pass(model, work, *, chaos, seed, report):
    """Multi-LoRA pass (ISSUE 15): the same seeded workload spread over
    3 resident adapters (+ base rows), with a 4th "hot" adapter loaded
    MID-STREAM and the tail of the workload targeted at it.

    Chaos layer: `serving.lora.load_fail` makes the mid-stream load
    fail typed — every hot-adapter request then sheds typed
    (AdapterNotLoaded) at the door, and the co-batched rows of the
    OTHER adapters must stay bit-identical to the clean lora pass;
    `serving.lora.evict_race` is armed across a forced slot-pressure
    load while the resident adapters are pinned by live requests — the
    refcount guard must refuse (counted), never evict live weights.
    Plus the usual transient/NaN chaos so adapter'd rows exercise
    retry and per-row quarantine. Returns ({idx: tokens}, affected)."""
    from paddle_tpu.serving import (AdapterLoadError, AdapterNotLoaded,
                                    AdapterRegistry, LoRAAdapter)
    from paddle_tpu.serving.lora.store import llama_lora_dims
    dims = llama_lora_dims(model.cfg)

    def mk_adapter(name, seed_off):
        return LoRAAdapter.random(name, 4, dims, seed=700 + seed_off)

    # slots=5 -> 4 usable: ad0..ad2 + hot fill the bucket, so the
    # evict-race load below MUST attempt an eviction
    reg = AdapterRegistry(dims, rank_buckets=(8,), slots=5)
    for i in range(3):
        reg.load(mk_adapter(f"ad{i}", i))
    adapters = [None if i % 5 == 4 else f"ad{i % 3}"
                for i in range(len(work))]
    hot_from = max(1, len(work) - max(4, len(work) // 8))
    for i in range(hot_from, len(work)):
        adapters[i] = "hot"

    eng = ServingEngine(
        model, clock=FakeClock(), default_ttl_s=TTL_S,
        retry_policy=RetryPolicy(max_retries=12, base_s=0.0,
                                 sleep=lambda s: None),
        lora=reg, **ENGINE_KW)
    armed = set()

    def arm(name, **kwargs):
        faults.inject(name, **kwargs)
        armed.add(name)

    if chaos:
        # the lora points are armed IN the loop, immediately before
        # the load they target — arming order, not luck, decides which
        # load fails
        arm("serving.engine.decode_step",
            exc=TransientDeviceError("soak: relay loss"),
            after=4, times=1)
        nan_rng = np.random.RandomState(seed + 5)
        arm("serving.engine.nan_logits",
            payload=lambda reqs: [nan_rng.randint(len(reqs))],
            after=6, times=1)

    idx_of = {}
    pending = list(enumerate(work))
    out = {}
    affected = set()
    steps = 0
    hot_loaded = False
    hot_attempted = False
    evict_race_done = False
    max_steps = MAX_STEPS_FACTOR * max(1, len(work))
    try:
        while pending or eng.has_work():
            admitted = 0
            while pending and admitted < 4:
                i, (p, m) = pending[0]
                if i >= hot_from and not hot_attempted:
                    break            # hot tail waits for the load
                try:
                    rid = eng.add_request(p, max_new_tokens=m,
                                          adapter=adapters[i])
                except EngineOverloaded:
                    break
                except AdapterNotLoaded:
                    # typed shed at the door (hot load failed): the
                    # request is affected; co-batched rows must not be
                    affected.add(i)
                    out[i] = []
                    pending.pop(0)
                    continue
                idx_of[rid] = i
                pending.pop(0)
                admitted += 1
            if pending and pending[0][0] >= hot_from and \
                    not hot_attempted:
                # mid-stream: the hot adapter loads only once its tail
                # of the workload reaches the head of the queue; under
                # chaos the load fails typed and the tail sheds typed
                hot_attempted = True
                if chaos:
                    arm("serving.lora.load_fail", payload=True, times=1)
                try:
                    eng.load_adapter(mk_adapter("hot", 9))
                    hot_loaded = True
                except AdapterLoadError:
                    hot_loaded = False
            if chaos and not evict_race_done and \
                    len(eng.scheduler.running) >= 2:
                # forced slot pressure while the residents are pinned
                # by live requests: "spare" fills the bucket's last
                # slot, "spare2" then needs an eviction — the armed
                # race makes the evictor ATTEMPT a pinned victim; the
                # refcount guard must refuse it (counted) and take the
                # idle "spare" instead
                evict_race_done = True
                try:
                    eng.load_adapter(mk_adapter("spare", 11))
                except AdapterLoadError:
                    pass
                arm("serving.lora.evict_race", payload=True, times=1)
                try:
                    eng.load_adapter(mk_adapter("spare2", 12))
                except AdapterLoadError:
                    pass
            for rid, tok in eng.step():
                out.setdefault(idx_of[rid], []).append(tok)
            steps += 1
            if steps > max_steps:
                raise AssertionError(
                    f"lora soak failed to drain after {steps} steps")

        reasons = {}
        for rid, i in idx_of.items():
            req = eng.requests.get(rid)
            assert req is not None, f"request {rid} evicted mid-soak"
            reasons[req.finish_reason] = reasons.get(
                req.finish_reason, 0) + 1
            if req.finish_reason in ("quarantined", "expired", "abort"):
                affected.add(i)
            out[i] = list(req.output_ids)

        # every adapter unpinned at drain; reclamation exact
        for name in reg.adapter_names():
            assert reg.refs_of(name) == 0, (name, reg.refs_of(name))
        reg.check_invariants()
        eng.reset_prefix_cache()
        assert eng.allocator.num_used == 0, "KV pages leaked"
        eng.allocator.check_invariants()

        snap = eng.metrics.snapshot()
        label = "lora_chaos" if chaos else "lora_clean"
        report[label] = {
            "steps": steps, "hot_loaded": hot_loaded,
            "finish_reasons": reasons, "affected": len(affected),
            "adapters_loaded": snap["adapters_loaded"],
            "adapters_evicted": snap["adapters_evicted"],
            "adapter_rejects": snap["adapter_rejects"],
            "adapter_load_failures": snap["adapter_load_failures"],
            "lora_evict_refusals": snap["lora_evict_refusals"],
            "step_retries": snap["step_retries"],
            "quarantined": snap["requests_quarantined"],
            "prefix_hits": snap["prefix_hits"],
            "adapter_mix_p50": snap.get("adapter_mix_p50"),
        }
        if chaos:
            fired = faults.fired_counts()
            report[f"fired_{label}"] = fired
            for pt in sorted(armed):
                assert fired.get(pt, 0) >= 1, \
                    f"armed fault point {pt} never fired"
        return out, affected
    finally:
        faults.clear()
        faults.reset_counts()
        eng.shutdown()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-spec", action="store_true",
                    help="skip the two speculative-decoding passes")
    ap.add_argument("--lora", action="store_true",
                    help="add the multi-LoRA clean + chaos passes "
                         "(ISSUE 15: mid-stream adapter load failure "
                         "sheds typed, evict-race guard, co-batched "
                         "bit-identity)")
    ap.add_argument("--no-int8", action="store_true",
                    help="skip the two int8-KV passes")
    ap.add_argument("--spill", action="store_true",
                    help="add the tiered-KV passes (ISSUE 17: spill "
                         "off/clean/chaos on a spill-pressure workload "
                         "— host_spill.* faults degrade to recompute "
                         "bit-identically, both pools reclaim, cached-"
                         "token rate beats the HBM-only ceiling)")
    ap.add_argument("--trace-out",
                    default=os.path.join("profiler_log",
                                         "soak_trace.json"),
                    help="where the traced pass exports its merged "
                         "chrome-trace JSON (ISSUE 10)")
    args = ap.parse_args(argv)

    cfg = LlamaConfig(vocab_size=128, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=1,
                      max_position_embeddings=128)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    work = make_workload(args.requests, args.seed)

    report = {"requests": args.requests, "seed": args.seed}
    t0 = time.perf_counter()
    clean, _ = run_workload(model, work, chaos=False, seed=args.seed,
                            report=report)
    chaotic, affected = run_workload(model, work, chaos=True,
                                     seed=args.seed, report=report)

    # ---- bit-identity of unaffected requests ------------------------
    diverged = [i for i in range(len(work))
                if i not in affected and chaotic.get(i) != clean.get(i)]
    assert not diverged, \
        f"unaffected requests diverged from the clean run: {diverged[:10]}"
    # the chaos run must actually have exercised the failure paths
    ch = report["chaos"]
    assert ch["step_retries"] >= 1 and ch["quarantined"] >= 1, ch
    report["unaffected_bit_identical"] = args.requests - len(affected)

    # ---- tracing overhead + trace export (ISSUE 10) ------------------
    # the SAME clean workload with per-request tracing ON: tokens must
    # be bit-identical (observation must not perturb), and the step-
    # loop time delta vs an untraced re-run IS the measured tracing
    # cost (tracing off is the default — nothing to measure there).
    # Methodology: every pass recompiles its programs (fresh engine ⇒
    # fresh jit closures), and XLA compile variance on a shared CPU box
    # (~±0.2 s) swamps the tracing signal in raw wall clock; single
    # 40 ms GC/dispatch spikes likewise dominate a window SUM. So the
    # arms are compared on the flight recorder's own per-step t_wall_ms
    # over the steady-state window (the bounded ring drops the early
    # compile-heavy steps), PAIRED by step number — both passes run the
    # identical schedule — and the estimator is the median paired delta
    # over the median untraced step: robust to load spikes in either
    # arm. Three interleaved reps, deltas POOLED across reps before the
    # median so slow load drift between passes cancels; per-rep medians
    # are printed alongside as the spread evidence.
    from paddle_tpu.serving import RequestTracer
    estimates = []
    all_deltas = []
    all_base = []
    tracer = None
    keep = {}

    def _step_ms(kp):
        return {r["step"]: r["t_wall_ms"] for r in kp["timeline"]}

    for rep in range(3):
        kp_u = {}
        warm, _ = run_workload(model, work, chaos=False, seed=args.seed,
                               report=report, label=f"warm_clean_{rep}",
                               keep=kp_u)
        assert warm == clean, "untraced re-run must be bit-identical"
        tracer = RequestTracer(max_completed=4 * max(1, args.requests))
        keep = {}
        traced, _ = run_workload(model, work, chaos=False,
                                 seed=args.seed, report=report,
                                 trace=tracer, label=f"traced_{rep}",
                                 keep=keep)
        div = [i for i in range(len(work))
               if traced.get(i) != clean.get(i)]
        assert not div, f"tracing changed greedy tokens: {div[:10]}"
        by_u, by_t = _step_ms(kp_u), _step_ms(keep)
        assert set(by_u) == set(by_t), "step windows diverged"
        deltas = sorted(by_t[s] - by_u[s] for s in by_u)
        base = sorted(by_u.values())
        med_delta = deltas[len(deltas) // 2]
        med_base = base[len(base) // 2]
        estimates.append(med_delta / max(med_base, 1e-9))
        all_deltas.extend(deltas)
        all_base.extend(base)
    all_deltas.sort()
    all_base.sort()
    med_base_ms = max(all_base[len(all_base) // 2], 1e-9)
    overhead = all_deltas[len(all_deltas) // 2] / med_base_ms
    report["trace_overhead"] = round(overhead, 4)
    report["traced_requests"] = tracer.num_completed
    # generous sanity bound only — wall-clock noise on a shared CPU box
    # must not flake the soak; the measured number is the evidence
    assert overhead < 0.5, \
        f"tracing overhead {overhead:.1%} is far beyond budget"

    # deterministic per-step cost bound: time EXACTLY what a traced
    # decode step adds (2 now_ns + the shared batched `span_many`, the
    # decode_step arg shape) against the median untraced step — the
    # precise ≤5% gate the wall-clock estimate above corroborates but,
    # on a shared box, cannot enforce without flaking
    mb = RequestTracer()
    rids = tuple(range(8))
    for rid in rids:
        mb.begin(rid, engine="microbench", prompt_len=16,
                 max_new_tokens=8)
    n_iter = 2000
    t1 = time.perf_counter()
    for _ in range(n_iter):
        t_tr = mb.now_ns()
        mb.span_many(rids, "decode_step", t_tr, mb.now_ns(),
                     engine="microbench", batch=8, bucket=[8, 8])
    per_step_ms = (time.perf_counter() - t1) * 1e3 / n_iter
    for rid in range(8):       # keep the microbench traces bounded
        mb.finish(rid, "stop")
    overhead_step = per_step_ms / med_base_ms
    report["trace_overhead_per_step"] = round(overhead_step, 4)
    assert overhead_step < 0.05, \
        f"per-step tracing cost {overhead_step:.2%} breaks the 5% budget"
    os.makedirs(os.path.dirname(args.trace_out) or ".", exist_ok=True)
    tracer.export(args.trace_out, flight_recorder=keep.get("timeline"))
    report["trace_out"] = args.trace_out

    if not args.no_spec:
        # ---- speculative-decoding passes (ISSUE 5) -------------------
        spec_clean, _ = run_workload(model, work, chaos=False,
                                     seed=args.seed, report=report,
                                     spec=True)
        # speculation must not change ANY greedy token vs plain decode
        # (same workload, same clock, no faults in either pass)
        spec_div = [i for i in range(len(work))
                    if spec_clean.get(i) != clean.get(i)]
        assert not spec_div, \
            f"spec decode changed greedy tokens: {spec_div[:10]}"
        sc = report["spec_clean"]
        assert sc["spec_accepted"] > 0 and sc["spec_steps"] > 0, sc
        # ... and fewer decode-side launches did the same work
        assert sc["spec_tokens_per_step"] > 1.0, sc

        spec_chaos, spec_aff = run_workload(model, work, chaos=True,
                                            seed=args.seed,
                                            report=report, spec=True)
        spec_div = [i for i in range(len(work))
                    if i not in spec_aff
                    and spec_chaos.get(i) != spec_clean.get(i)]
        assert not spec_div, ("unaffected requests diverged under spec "
                              f"chaos: {spec_div[:10]}")
        sx = report["spec_chaos"]
        assert sx["step_retries"] >= 1 and sx["quarantined"] >= 1, sx
        assert sx["spec_rollback"] >= 1, sx
        report["spec_unaffected_bit_identical"] = \
            args.requests - len(spec_aff)

    if not args.no_int8:
        # ---- int8-KV passes (ISSUE 6) --------------------------------
        # quantize-on-write is deterministic, so the int8 pair carries
        # the SAME bit-identity contract as the full-precision pair:
        # chaos may only change affected (quarantined/expired/aborted)
        # requests. Cross-dtype token equality is NOT asserted — int8
        # attention is allowed its documented rel-err budget.
        i8_clean, _ = run_workload(model, work, chaos=False,
                                   seed=args.seed, report=report,
                                   kv_dtype="int8")
        i8_chaos, i8_aff = run_workload(model, work, chaos=True,
                                        seed=args.seed, report=report,
                                        kv_dtype="int8")
        i8_div = [i for i in range(len(work))
                  if i not in i8_aff
                  and i8_chaos.get(i) != i8_clean.get(i)]
        assert not i8_div, ("unaffected requests diverged under int8 "
                            f"chaos: {i8_div[:10]}")
        ic = report["int8_chaos"]
        assert ic["step_retries"] >= 1 and ic["quarantined"] >= 1, ic
        report["int8_unaffected_bit_identical"] = \
            args.requests - len(i8_aff)

    if args.lora:
        # ---- multi-LoRA passes (ISSUE 15) ----------------------------
        lora_clean, lc_aff = run_lora_pass(model, work, chaos=False,
                                           seed=args.seed, report=report)
        assert not lc_aff and report["lora_clean"]["hot_loaded"], \
            report["lora_clean"]
        assert report["lora_clean"]["prefix_hits"] >= 1
        lora_chaos, lora_aff = run_lora_pass(model, work, chaos=True,
                                             seed=args.seed,
                                             report=report)
        lx = report["lora_chaos"]
        # the mid-stream load failure really shed the hot tail typed...
        assert not lx["hot_loaded"] and lx["adapter_load_failures"] >= 1
        assert lx["adapter_rejects"] >= 1 and len(lora_aff) >= 1, lx
        # ...the evict-race guard refused the pinned victim...
        assert lx["lora_evict_refusals"] >= 1, lx
        # ...and no co-batched row of any OTHER adapter moved a bit
        lora_div = [i for i in range(len(work))
                    if i not in lora_aff
                    and lora_chaos.get(i) != lora_clean.get(i)]
        assert not lora_div, ("unaffected requests diverged under lora "
                              f"chaos: {lora_div[:10]}")
        report["lora_unaffected_bit_identical"] = \
            args.requests - len(lora_aff)

    if args.spill:
        # ---- tiered-KV spill passes (ISSUE 17) -----------------------
        # a spill-pressure workload on a shrunken device pool, three
        # ways: host tier off (the HBM-only ceiling), on (clean), and
        # on with every host_spill.* read fault armed
        swork = make_spill_workload(args.requests, args.seed)
        off_kw = dict(num_pages=24)
        on_kw = dict(num_pages=24, host_spill_pages=32)
        s_off, _ = run_workload(model, swork, chaos=False,
                                seed=args.seed, report=report,
                                extra_kw=off_kw, label="spill_off")
        s_clean, _ = run_workload(model, swork, chaos=False,
                                  seed=args.seed, report=report,
                                  extra_kw=on_kw, label="spill_clean")
        # the tier is invisible in the tokens (EVERY request — no
        # faults in either pass) ...
        s_div = [i for i in range(len(swork))
                 if s_clean.get(i) != s_off.get(i)]
        assert not s_div, \
            f"spill tier changed greedy tokens: {s_div[:10]}"
        sc = report["spill_clean"]
        assert sc["kv_pages_demoted"] > 0 and \
            sc["kv_pages_promoted"] > 0 and \
            sc["host_prefix_hits"] >= 1, sc
        # ... while serving MORE cached tokens at the same device pool
        # (the perf_opt acceptance: host capacity raises the hit rate
        # above the HBM-only ceiling)
        assert sc["cached_tokens"] > \
            report["spill_off"]["cached_tokens"], \
            (sc["cached_tokens"], report["spill_off"]["cached_tokens"])
        s_chaos, s_aff = run_workload(model, swork, chaos=False,
                                      seed=args.seed, report=report,
                                      extra_kw=on_kw, spill_chaos=True,
                                      label="spill_chaos")
        # all three read faults degrade to recompute: NOTHING is
        # affected and EVERY token matches the clean spill pass
        assert not s_aff, s_aff
        s_div = [i for i in range(len(swork))
                 if s_chaos.get(i) != s_clean.get(i)]
        assert not s_div, \
            f"spill faults changed greedy tokens: {s_div[:10]}"
        sx = report["spill_chaos"]
        assert all(c >= 1 for c in sx["spill_faults"]), sx
        report["spill_bit_identical"] = args.requests

    report["wall_s"] = round(time.perf_counter() - t0, 2)
    print(json.dumps(report))
    # ---- final report through the observability paths (ISSUE 10) -----
    # per-phase latency + flight-recorder digest from the traced pass,
    # and the engine's Prometheus exposition — the same renderers
    # production scrapes/postmortems use, exercised on every soak
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    print(trace_report.report(trace_report.load(args.trace_out)))
    print("== metrics exposition (traced clean pass) ==")
    print(keep.get("prometheus", ""), end="")
    print(f"trace_overhead={report['trace_overhead']:+.2%} "
          f"(median paired per-step delta over the steady-state "
          f"window; reps {['%+.2f%%' % (100 * e) for e in estimates]}) "
          f"per_step_bound={report['trace_overhead_per_step']:.2%}")
    print("SOAK_SERVING_OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"SOAK_SERVING_FAILED: {e}", file=sys.stderr)
        sys.exit(1)
