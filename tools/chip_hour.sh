#!/bin/sh
# THE CHIP HOUR (VERDICT r3/r4 item 1): run on a LIVE axon relay only.
#   sh tools/relay_check.sh && sh tools/chip_hour.sh
# Rules (CLAUDE.md): ONE TPU python process at a time, generous
# timeouts, SIGTERM not SIGKILL. Each step is a separate process so a
# wedged step doesn't hold the grant.
set -x
cd "$(dirname "$0")/.."

# 1. claim + device sanity (fast; watchdog via timeout -s TERM)
timeout -s TERM 300 python -c "import jax; print(jax.devices())" || exit 1

# 2. Pallas pack validation on the real chip (interpret=False):
#    flash fwd/bwd at S in {2k, 8k, 32k}, varlen/flashmask, paged
#    folded grid, rms_norm_rows. Plain python (pytest is CPU-pinned).
timeout -s TERM 900 python - <<'EOF'
import numpy as np, jax, jax.numpy as jnp
import paddle_tpu  # registers kernels
from paddle_tpu.kernels.flash_attention import flash_attention_bshd
print("devices:", jax.devices())
for S in (2048, 8192, 32768):
    B, H, D = 1, 4, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    out = flash_attention_bshd(q, k, v, causal=True)
    jax.block_until_ready(out)
    print(f"flash fwd S={S} OK", np.asarray(out[0,0,0,:2], np.float32))
    if S <= 8192:  # bwd at 2k/8k
        def loss(q, k, v):
            return flash_attention_bshd(q, k, v, causal=True).astype(
                jnp.float32).sum()
        g = jax.grad(loss)(q, k, v)
        jax.block_until_ready(g)
        print(f"flash bwd S={S} OK")
print("FLASH_CHIP_OK")
EOF

timeout -s TERM 600 python - <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.paged_attention import paged_attention_decode
B, H, KVH, D, page, pages_per_seq = 4, 8, 8, 128, 16, 8
num_pages = B * pages_per_seq
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, H, D), jnp.bfloat16)
kc = jnp.asarray(rng.randn(num_pages, KVH, page, D), jnp.bfloat16)
vc = jnp.asarray(rng.randn(num_pages, KVH, page, D), jnp.bfloat16)
tables = jnp.arange(num_pages, dtype=jnp.int32).reshape(B, pages_per_seq)
lens = jnp.full((B,), page * pages_per_seq, jnp.int32)
out = paged_attention_decode(q, kc, vc, tables, lens)
jax.block_until_ready(out)
print("PAGED_CHIP_OK", out.shape)
EOF

timeout -s TERM 600 python - <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.fused_norm import rms_norm_rows
x = jnp.asarray(np.random.RandomState(0).randn(256, 512), jnp.float32)
w = jnp.ones((512,), jnp.float32)
out = rms_norm_rows(x, w, eps=1e-6)
jax.block_until_ready(out)
ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2)
print("RMSNORM_CHIP_OK")
EOF

# 3. the real benchmark numbers
timeout -s TERM 900 python bench.py
timeout -s TERM 1500 python bench_ops.py --write-md

echo "CHIP_HOUR_DONE — commit BENCH_OPS.md and record numbers"
