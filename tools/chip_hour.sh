#!/bin/sh
# THE CHIP HOUR (VERDICT r3/r4 item 1): run on a LIVE axon relay only.
#   sh tools/relay_check.sh && sh tools/chip_hour.sh
# Rules (CLAUDE.md): ONE TPU python process at a time, generous
# timeouts, SIGTERM first (a SIGKILLed client leaks the grant; the
# delayed -k KILL is the lesser evil vs holding the grant forever).
# Each step is a separate process so a wedged step doesn't hold the
# grant; failures are COUNTED and the script exits non-zero if any
# validation failed — it still runs the benchmarks (they have their own
# fallback chains) so a partial live window isn't wasted.
set -x
cd "$(dirname "$0")/.."
# Step scripts live in /tmp, so python puts /tmp (not the repo) on
# sys.path; the repo root must come from PYTHONPATH.
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export PYTHONPATH
FAILED=""

step() {  # step <name> <timeout_s> <<'EOF' python EOF  (via stdin file)
  name="$1"; t="$2"; shift 2
  timeout -s TERM -k 60 "$t" python "$@" || FAILED="$FAILED $name"
}

# 1. claim + device sanity
timeout -s TERM -k 60 300 python -c "import jax; print(jax.devices())" \
  || { echo "CHIP_HOUR_ABORT: device claim failed"; exit 1; }

# 2. Pallas pack validation on the real chip (interpret=False). Plain
#    python (pytest is CPU-pinned).
cat > /tmp/chip_flash.py <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.flash_attention import flash_attention_bshd
print("devices:", jax.devices())
for S in (2048, 8192, 32768):
    B, H, D = 1, 4, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    out = flash_attention_bshd(q, k, v, causal=True)
    jax.block_until_ready(out)
    print(f"flash fwd S={S} OK", np.asarray(out[0, 0, 0, :2], np.float32))
    def loss(q, k, v):
        return flash_attention_bshd(q, k, v, causal=True).astype(
            jnp.float32).sum()
    g = jax.grad(loss)(q, k, v)
    jax.block_until_ready(g)
    print(f"flash bwd S={S} OK")
print("FLASH_CHIP_OK")
EOF
step flash 1200 /tmp/chip_flash.py

cat > /tmp/chip_varlen.py <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.flash_attention import (
    flash_attention_varlen_bshd, flashmask_attention_bshd)
B, S, H, D = 1, 2048, 4, 128
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
# two packed sequences of S/2
seg = jnp.concatenate([jnp.zeros((B, S // 2), jnp.int32),
                       jnp.ones((B, S // 2), jnp.int32)], axis=1)
out = flash_attention_varlen_bshd(q, k, v, seg, seg, causal=True)
jax.block_until_ready(out)
print("VARLEN_CHIP_OK", out.shape)
# flashmask: causal bounds (every key visible to rows >= its index)
idx = jnp.broadcast_to(
    jnp.full((S, 1), S, jnp.int32)[None, None], (B, 1, S, 1))
out2 = flashmask_attention_bshd(q, k, v, idx, causal=True)
jax.block_until_ready(out2)
print("FLASHMASK_CHIP_OK", out2.shape)
EOF
step varlen_flashmask 900 /tmp/chip_varlen.py

cat > /tmp/chip_paged.py <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.paged_attention import paged_attention_decode
B, H, KVH, D, page, pages_per_seq = 4, 8, 8, 128, 16, 8
num_pages = B * pages_per_seq
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, H, D), jnp.bfloat16)
kc = jnp.asarray(rng.randn(num_pages, KVH, page, D), jnp.bfloat16)
vc = jnp.asarray(rng.randn(num_pages, KVH, page, D), jnp.bfloat16)
tables = jnp.arange(num_pages, dtype=jnp.int32).reshape(B, pages_per_seq)
lens = jnp.full((B,), page * pages_per_seq, jnp.int32)
out = paged_attention_decode(q, kc, vc, tables, lens)
jax.block_until_ready(out)
print("PAGED_CHIP_OK", out.shape)
EOF
step paged 600 /tmp/chip_paged.py

cat > /tmp/chip_rmsnorm.py <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.fused_norm import rms_norm_rows
x = jnp.asarray(np.random.RandomState(0).randn(256, 512), jnp.float32)
w = jnp.ones((512,), jnp.float32)
out = rms_norm_rows(x, w, eps=1e-6)
jax.block_until_ready(out)
ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2)
print("RMSNORM_CHIP_OK")
EOF
step rms_norm 600 /tmp/chip_rmsnorm.py

cat > /tmp/chip_fused_opt.py <<'EOF'
# Fused bucketed AdamW (ISSUE 9) on the real chip: Mosaic-compile the
# kernel at the flagship recipe (bf16 grads, fp32 master, bf16
# moments), check it against the identical XLA composition, and
# device_time both so the fused-vs-XLA decision row lands with real
# numbers (GB/s math mirrors bench_ops.bench_optimizer_update).
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.kernels.fused_optimizer import (
    LANES, adamw_scalars, adamw_update_bytes, fused_adamw_bucket)
from paddle_tpu.kernels.timing import device_time
print("devices:", jax.devices())
rows = 131072                      # 16.8M elems -> ~0.34 GB of state
rng = np.random.RandomState(0)
g = jnp.asarray(rng.randn(rows, LANES), jnp.bfloat16)
w = jnp.asarray(rng.randn(rows, LANES), jnp.float32)
m = jnp.zeros((rows, LANES), jnp.bfloat16)
v = jnp.zeros((rows, LANES), jnp.bfloat16)
s = adamw_scalars(3e-4, 0.9, 0.999, 1e-8, 0.01, 7)
pl_fn = jax.jit(lambda g, w, m, v: fused_adamw_bucket(
    g, w, m, v, s, param_dtype=jnp.bfloat16, use_pallas=True))
xla_fn = jax.jit(lambda g, w, m, v: fused_adamw_bucket(
    g, w, m, v, s, param_dtype=jnp.bfloat16, use_pallas=False))
outs_pl = pl_fn(g, w, m, v)
outs_x = xla_fn(g, w, m, v)
err = max(float(jnp.abs(a.astype(jnp.float32) -
                        b.astype(jnp.float32)).max())
          for a, b in zip(outs_pl, outs_x))
assert err < 1e-4, f"fused-vs-XLA mismatch {err}"
nbytes = adamw_update_bytes(rows * LANES, param_width=2, moment_width=2,
                            has_master=True)
for name, fn in (("pallas", pl_fn), ("xla", xla_fn)):
    dt = device_time(fn, g, w, m, v)
    gbps = nbytes / dt / 1e9 if dt > 0 else float("nan")
    print(f"FUSED_OPT {name} ms={dt * 1e3:.3f} GB/s={gbps:.1f}")
print("FUSED_OPT_CHIP_OK")
EOF
step fused_opt 900 /tmp/chip_fused_opt.py

# 2b. COMM ladder (ISSUE 12): device_time a psum/all-gather ladder over
#     the real mesh and report achieved GB/s against the bytes
#     profiler/comm.py accounts for the SAME compiled programs
#     (accounting-vs-hand-computed equality hard-asserts ON_TPU with
#     >1 device; a single-chip grant reports the honest 0-byte note).
step comm 900 tools/chip_comm.py

# 2c. numeric parity on chip (kernels execute AND match XLA references)
step parity 900 tools/chip_parity.py

# 2d. serving path: compiled decode loop vs eager + int8 parity +
#     spec/multi-step/TP/LoRA probes + the tiered-KV spill probe
#     (ISSUE 17: forced-spill cached-token rate vs HBM-only, identity
#     hard-gated, first real-relay run of the promotion host->device
#     copy) + the DISAGG probe (ISSUE 18, staged chip-blind: the
#     prefill-role handoff -> export -> codec round trip -> adopt ->
#     decode path has only run on CPU; first chip run exercises the
#     exported page bytes through device fetch + host re-upload)
step serving 1500 tools/chip_serving.py

# 2e. BASELINE config ladder: ResNet/ERNIE/DiT/Qwen2-MoE train steps
step ladder 1800 tools/chip_ladder.py

# 3. the real benchmark numbers. bench.py never exits non-zero by
#    design, but timeout(1) itself exits 124/143 on a wedge — count
#    that; bench_ops failures are recorded like validation steps. The
#    JSON line is kept for the COST_MFU comparison below.
if timeout -s TERM -k 60 900 python bench.py > /tmp/bench_fused_line.json
then :; else FAILED="$FAILED bench"; fi
cat /tmp/bench_fused_line.json

# 3a. COST_MFU (ISSUE 11, chip-blind staging): cost-analysis MFU vs the
#     hand-formula MFU for the flagship config, from the bench line's
#     analytic_flops (XLA cost_analysis of the compiled step). Reading
#     rule (profiler/cost.py): Pallas custom calls count ZERO flops, so
#     under pallas_flash the analytic number undercounts by about
#     attn_flops_share; under xla_sdpa the two must agree within 5%.
#     Stdlib-only (no second TPU claim) — records, never gates.
cat > /tmp/chip_cost_mfu.py <<'EOF'
import json, sys
rec = None
for line in open("/tmp/bench_fused_line.json"):
    try:
        d = json.loads(line)
    except ValueError:
        continue
    if isinstance(d, dict) and "metric" in d and "error" not in d:
        rec = d
if rec is None:
    print("COST_MFU_SKIP: no bench record"); sys.exit(0)
measured, analytic = rec.get("value"), rec.get("analytic_mfu")
share = rec.get("attn_flops_share", 0.0)
if not measured or analytic is None:
    print(f"COST_MFU_SKIP: analytic fields null ({rec.get('attention')})")
    sys.exit(0)
ratio = analytic / measured
expect = 1.0 - share if rec.get("attention") == "pallas_flash" else 1.0
print(f"COST_MFU measured={measured} analytic={analytic} "
      f"ratio={ratio:.4f} expected~{expect:.4f} "
      f"attention={rec.get('attention')} "
      f"peak_hbm_gb={(rec.get('peak_hbm_bytes') or 0) / 1e9:.2f}")
print("COST_MFU_OK" if abs(ratio - expect) < 0.05
      else f"COST_MFU_DRIFT: |{ratio:.4f} - {expect:.4f}| >= 0.05")
EOF
# CPU-pinned + timeouted like every step: a bare python would claim
# the (possibly leaked) TPU grant via sitecustomize and block forever
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  timeout -s TERM -k 10 120 python /tmp/chip_cost_mfu.py \
  || FAILED="$FAILED cost_mfu"
step bench_ops 2700 bench_ops.py --write-md

# 3b. flagship A/B re-run (ISSUE 9): the first bench line leads with
#     the fused optimizer; this one pins BENCH_FUSED_OPT=0 so the SAME
#     window also records the round-4 non-fused configuration. The
#     fallback chain only degrades on exceptions — a fused config that
#     runs but is slower can only be caught by comparing these two
#     lines (the "optimizer" field labels each).
BENCH_FUSED_OPT=0 timeout -s TERM -k 60 900 python bench.py \
  || FAILED="$FAILED bench_nonfused"

if [ -n "$FAILED" ]; then
  echo "CHIP_HOUR_FAILURES:$FAILED"
  exit 1
fi
echo "CHIP_HOUR_DONE — commit BENCH_OPS.md and record numbers"
