"""On-chip serving-path validation: compiled decode loop + int8 parity.

1. LlamaForCausalLM.generate(use_jit=True) — prefill + whole decode
   loop + sampling as ONE XLA program — on the real chip, checked
   against the eager decode loop token-for-token (greedy).
2. weight_only_linear int8 vs the bf16 matmul it approximates.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

print("devices:", jax.devices())

cfg = LlamaConfig(vocab_size=1024, hidden_size=256, intermediate_size=512,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=256)
paddle.seed(0)
model = LlamaForCausalLM(cfg)
model.bfloat16()
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)))

# the greedy eager-vs-jit gate is a CHIP gate: on CPU the two paths
# compile to different XLA programs whose rounding legitimately
# diverges at near-tie logits (0.79 match measured at the PR-5 HEAD),
# so off-chip this reports instead of hard-asserting (ISSUE 6
# satellite — the pre-existing CPU failure mode)
ON_TPU = jax.default_backend() == "tpu"

out_eager = model.generate(ids, max_new_tokens=24, temperature=0.0)
out_jit = model.generate(ids, max_new_tokens=24, temperature=0.0,
                         use_jit=True)
a = np.asarray(out_eager._data if hasattr(out_eager, "_data") else out_eager)
b = np.asarray(out_jit._data if hasattr(out_jit, "_data") else out_jit)
match = (a == b).mean()
print(f"decode greedy eager-vs-jit token match: {match:.3f}")
if ON_TPU:
    # greedy at temperature 0 must agree EXACTLY on chip — one flipped
    # token cascades, so anything < 1.0 is a real regression
    assert match == 1.0, (a, b)
    print("SERVING_JIT_CHIP_OK", a.shape)
else:
    print(f"SERVING_JIT_CPU_REPORT_ONLY match={match:.3f} "
          "(hard gate runs on TPU)")

# sampled path executes (no parity claim — different RNG streams ok)
out_s = model.generate(ids, max_new_tokens=8, temperature=0.8, top_p=0.9,
                       use_jit=True, seed=7)
print("SERVING_SAMPLED_CHIP_OK",
      np.asarray(out_s._data if hasattr(out_s, "_data") else out_s).shape)

# --- int8 weight-only parity -----------------------------------------
from paddle_tpu.nn.quant import weight_quantize, weight_only_linear
K, N, M = 1024, 1024, 64
w = paddle.to_tensor((rng.randn(K, N) * 0.02).astype(np.float32))
x = paddle.to_tensor(rng.randn(M, K).astype(np.float32))
qw, scale = weight_quantize(w, algo="weight_only_int8")
y_q = np.asarray(weight_only_linear(
    x, qw, weight_scale=scale, weight_dtype="int8")._data, np.float32)
y_f = np.asarray((x._data @ w._data), np.float32)
rel = np.abs(y_q - y_f).max() / (np.abs(y_f).max() + 1e-9)
print(f"int8 weight-only rel_err {rel:.4f}")
assert rel < 2e-2, rel
print("INT8_CHIP_OK")

# --- ServingEngine continuous-batching decode throughput --------------
# VERDICT open item #9 ("measure serving decode"): 8 requests decode in
# ONE batched program over the real Pallas paged kernel. Each step()
# host-fetches the sampled tokens, which is the only honest sync over
# the axon relay, so wall-clock across steps is a true step time.
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.scheduler import RequestState

eng = ServingEngine(model, num_pages=128, page_size=16,
                    batch_buckets=[8], prefill_buckets=[16, 128],
                    pages_buckets=[8], temperature=0.0)
for _ in range(8):
    eng.add_request(rng.randint(0, cfg.vocab_size, (12,)).tolist(),
                    max_new_tokens=100)
# warm: prefills + first decode launch (compiles both programs)
while not all(r.state is RequestState.DECODE
              for r in eng.requests.values()):
    eng.step()
eng.step()
import time
N_STEPS = 64
t0 = time.perf_counter()
for _ in range(N_STEPS):
    eng.step()
dt = time.perf_counter() - t0
tps = 8 * N_STEPS / dt
print(f"serving engine: batch=8 decode {dt / N_STEPS * 1e3:.2f} ms/step "
      f"SERVING_ENGINE_TOKS_PER_S {tps:.1f}")
# the engine report goes out through the observability paths (ISSUE 10)
# — the Prometheus exposition and the flight-recorder digest — so the
# chip probe exercises the same renderers production scrapes use
# (host-side only: chip-blind by construction)
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import trace_report
print("serving engine exposition:")
print(eng.metrics.prometheus_text(), end="")
print(trace_report.format_flight_recorder(eng.timeline()))
assert eng.num_compiled_programs <= eng.max_program_count()

# --- failure-mode probe (ISSUE 3): abort + TTL on the real chip -------
# Two of the decoding requests are aborted mid-flight and two more are
# added with a microscopic TTL; the engine must drain cleanly, donate
# the aborted KV to the radix tree, and report the failure counters.
live = [r for r in eng.requests.values()
        if r.state is RequestState.DECODE][:2]
for r in live:
    assert eng.abort(r.request_id)
for _ in range(2):
    eng.add_request(rng.randint(0, cfg.vocab_size, (12,)).tolist(),
                    max_new_tokens=50, ttl_s=1e-6)
eng.run()
snap = eng.metrics.snapshot()
fail_keys = ("requests_aborted", "deadline_expired", "requests_shed",
             "step_retries", "requests_quarantined", "engine_failures")
print("serving failure counters:",
      {k: snap[k] for k in fail_keys})
print(trace_report.format_flight_recorder(eng.timeline()))
assert snap["requests_aborted"] == 2 and snap["deadline_expired"] == 2
assert snap["requests_quarantined"] == 0 and snap["engine_failures"] == 0
eng.reset_prefix_cache()
assert eng.allocator.num_used == 0
eng.shutdown()
print("SERVING_ENGINE_CHIP_OK SERVING_FAULTS_CHIP_OK")

# --- shared-prefix throughput probe (ISSUE 2) --------------------------
# 8 requests sharing a 96-token system-prompt-style prefix, radix cache
# on vs off. The first request warms the tree; the other 7 should serve
# the shared pages straight from cache. TTFT and total wall-clock are
# printed (not asserted — chip variance stays out of the gate); the
# counter assertions ARE the gate: the hit accounting must be exact.
shared = rng.randint(0, cfg.vocab_size, (96,)).tolist()
tails = [rng.randint(0, cfg.vocab_size, (8,)).tolist() for _ in range(8)]
for cache_on in (True, False):
    eng = ServingEngine(model, num_pages=256, page_size=16,
                        batch_buckets=[8], prefill_buckets=[128],
                        pages_buckets=[8], temperature=0.0,
                        enable_prefix_cache=cache_on)
    t0 = time.perf_counter()
    first = eng.add_request(shared + tails[0], max_new_tokens=16)
    eng.run()                       # warm request donates the prefix
    rest = [eng.add_request(shared + t, max_new_tokens=16)
            for t in tails[1:]]
    eng.run()
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    label = "on" if cache_on else "off"
    print(f"shared-prefix cache={label}: wall {wall:.3f}s "
          f"prefill_tokens {snap['prefill_tokens']} "
          f"skipped {snap['prefill_tokens_skipped']} "
          f"hit_rate {snap.get('prefix_hit_rate', 0)} "
          f"ttft_p50_ms {snap.get('ttft_p50_ms')}")
    if cache_on:
        assert snap["prefix_hits"] == 7, snap
        assert snap["prefill_tokens_skipped"] >= 7 * 96, snap
        print(f"SERVING_PREFIX_CACHE_CHIP_OK skipped="
              f"{snap['prefill_tokens_skipped']}")
    eng.reset_prefix_cache()
    assert eng.allocator.num_used == 0
    eng.shutdown()

# --- speculative-decoding probe (ISSUE 5) ------------------------------
# NgramProposer over a repetitive (summarization-shaped) workload:
# tok/s at batch {1, 8} x K in {2, 4, 8} against the plain-decode
# baseline, plus acceptance rate and the per-sequence tokens-per-step
# multiplier. Timing is fetch-synced by construction: every step()
# host-fetches the emitted tokens (the only honest sync over the axon
# relay — CLAUDE.md timing landmine #1), so wall-clock across a drain
# is a true serving time. Throughput is printed, not asserted (chip
# variance stays out of the gate); the gates are greedy bit-identity
# vs plain decode and exact reclamation. Lands chip-blind: CPU runs of
# the same code path are pinned by tests/test_serving_spec.py.
from paddle_tpu.serving import NgramProposer

spec_rng = np.random.RandomState(3)
cycle = spec_rng.randint(0, cfg.vocab_size, (6,)).tolist()
SPEC_PROMPT = (cycle * 12)[:64]          # repetitive: ngram-friendly
SPEC_NEW = 48


def run_spec_probe(batch, k, proposer):
    eng = ServingEngine(model, num_pages=256, page_size=16,
                        batch_buckets=[8], prefill_buckets=[64],
                        pages_buckets=[8], temperature=0.0,
                        proposer=proposer,
                        spec_k=(k or 1), spec_buckets=[k] if k else None)
    t0 = time.perf_counter()
    rids = [eng.add_request(SPEC_PROMPT, max_new_tokens=SPEC_NEW)
            for _ in range(batch)]
    out = eng.run()
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    eng.reset_prefix_cache()
    assert eng.allocator.num_used == 0
    assert eng.num_compiled_programs <= eng.max_program_count()
    eng.shutdown()
    toks = sum(len(out[r]) for r in rids)
    return {i: out[r] for i, r in enumerate(rids)}, toks / wall, snap


for batch in (1, 8):
    base_out, base_tps, _ = run_spec_probe(batch, None, None)
    print(f"spec-decode baseline: batch={batch} plain decode "
          f"{base_tps:.1f} tok/s")
    for k in (2, 4, 8):
        out, tps, snap = run_spec_probe(batch, k, NgramProposer())
        # greedy identity is a CHIP gate for the same reason as the
        # eager-vs-jit one above: this probe's model is bf16, and on
        # CPU the decode and verify programs (different shapes) round
        # near-tie bf16 logits differently — pre-existing at the PR-5
        # HEAD (16/48 match at batch=1 K=2), report-only off chip.
        # The f32 CPU identity contract stays pinned by
        # tests/test_serving_spec.py.
        if ON_TPU:
            assert out == base_out, f"spec K={k} changed greedy tokens"
        elif out != base_out:
            m = sum(a == b for bo, so in zip(base_out.values(),
                                             out.values())
                    for a, b in zip(bo, so))
            t = sum(len(v) for v in base_out.values())
            print(f"SPEC_CPU_REPORT_ONLY batch={batch} K={k} "
                  f"match={m}/{t} (hard gate runs on TPU)")
        print(f"SPEC_DECODE_CHIP batch={batch} K={k} "
              f"tok_s={tps:.1f} speedup={tps / base_tps:.2f}x "
              f"accept_rate={snap.get('spec_acceptance_rate')} "
              f"tokens_per_step={snap.get('spec_tokens_per_step')}")
        assert snap["spec_accepted_tokens"] > 0
print("SPEC_DECODE_CHIP_OK")

# --- quantized decode path probe (ISSUE 6) -----------------------------
# int8 KV pages + weight-only int8: decode throughput at batch 8 vs the
# full-precision engine, greedy token match fraction, and the doubled
# page capacity at fixed pool bytes. The rel-err budget asserted on
# chip: >= 90% token match (the per-step attention error is ~0.007 —
# chip_parity pins the kernel-level number; token flips only happen at
# near-tie logits). Throughput is printed, not asserted (chip variance
# stays out of the gate).
QPROMPTS = [rng.randint(0, cfg.vocab_size, (12,)).tolist()
            for _ in range(8)]


def run_quant_probe(kv_dtype=None, wq=None):
    import paddle_tpu as _p
    _p.seed(0)
    qmodel = LlamaForCausalLM(cfg)
    qmodel.bfloat16()
    eng = ServingEngine(qmodel, num_pages=128, page_size=16,
                        batch_buckets=[8], prefill_buckets=[16, 128],
                        pages_buckets=[8], temperature=0.0,
                        kv_dtype=kv_dtype, wq=wq)
    t0 = time.perf_counter()
    rids = [eng.add_request(p, max_new_tokens=32) for p in QPROMPTS]
    out = eng.run()
    wall = time.perf_counter() - t0
    toks = [out[r] for r in rids]
    eng.reset_prefix_cache()
    assert eng.allocator.num_used == 0
    assert eng.num_compiled_programs <= eng.max_program_count()
    snap = eng.metrics.snapshot()
    eng.shutdown()
    return toks, sum(len(t) for t in toks) / wall, snap


full_toks, full_tps, full_snap = run_quant_probe()
for label, kvd, wq in (("int8kv", "int8", None),
                       ("int8kv+wq", "int8", "int8")):
    q_toks, q_tps, q_snap = run_quant_probe(kvd, wq)
    total = sum(len(t) for t in full_toks)
    match = sum(a == b for ft, qt in zip(full_toks, q_toks)
                for a, b in zip(ft, qt)) / total
    print(f"QUANT_DECODE_CHIP {label}: tok_s={q_tps:.1f} "
          f"(full {full_tps:.1f}, {q_tps / full_tps:.2f}x) "
          f"token_match={match:.3f} "
          f"bytes/token {q_snap['kv_bytes_per_token']} vs "
          f"{full_snap['kv_bytes_per_token']}")
    if ON_TPU:
        assert match >= 0.9, f"{label} token match {match}"
    assert q_snap["kv_bytes_per_token"] * 1.7 <= \
        full_snap["kv_bytes_per_token"]

# page capacity at fixed pool bytes (pure geometry, asserted anywhere)
from paddle_tpu.kernels.paged_attention import paged_page_bytes
pb_full = paged_page_bytes(cfg.num_key_value_heads, 16,
                           cfg.hidden_size // cfg.num_attention_heads)
pb_int8 = paged_page_bytes(cfg.num_key_value_heads, 16,
                           cfg.hidden_size // cfg.num_attention_heads,
                           "int8")
POOL = 64 << 20
print(f"page capacity at {POOL >> 20} MB: bf16 {POOL // pb_full} "
      f"int8 {POOL // pb_int8} ({POOL // pb_int8 / (POOL // pb_full):.2f}x)")
assert POOL // pb_int8 >= 1.85 * (POOL // pb_full)
print("QUANT_DECODE_CHIP_OK")

# --- multi-step decode probe (ISSUE 13) --------------------------------
# K decode iterations per compiled launch vs the K=1 baseline: tok/s at
# K in {1, 4, 8, 16} over the same 8-request workload. Every step()
# host-fetches the launch's tokens (the only honest sync over the axon
# relay — CLAUDE.md timing landmine #1), so wall-clock across a drain
# is a true serving time; at ~7 ms host round trip per launch, K
# amortizes the dominant decode cost and the tok/s ladder IS the
# measured win. Greedy bit-identity vs K=1 is a CHIP gate (ON_TPU —
# this probe's model is bf16 and CPU rounds near-tie logits
# differently across program shapes; the f32 CPU identity contract is
# pinned by tests/test_serving_multi.py); tokens-per-launch >= 0.9 K
# at full batch is host bookkeeping and asserts anywhere.
MD_PROMPTS = [rng.randint(0, cfg.vocab_size, (12,)).tolist()
              for _ in range(8)]
MD_NEW = 48


def run_multi_probe(k):
    import paddle_tpu as _p
    _p.seed(0)
    mmodel = LlamaForCausalLM(cfg)
    mmodel.bfloat16()
    eng = ServingEngine(mmodel, num_pages=256, page_size=16,
                        batch_buckets=[8], prefill_buckets=[16, 128],
                        pages_buckets=[8], temperature=0.0,
                        decode_steps=k, multi_buckets=[k] if k > 1
                        else None)
    t0 = time.perf_counter()
    rids = [eng.add_request(p, max_new_tokens=MD_NEW)
            for p in MD_PROMPTS]
    out = eng.run()
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    assert eng.num_compiled_programs <= eng.max_program_count()
    eng.reset_prefix_cache()
    assert eng.allocator.num_used == 0
    eng.shutdown()
    toks = [out[r] for r in rids]
    return toks, sum(len(t) for t in toks) / wall, snap


md_base, md_base_tps, _ = run_multi_probe(1)
print(f"multi-decode baseline: K=1 {md_base_tps:.1f} tok/s")
for K in (4, 8, 16):
    md_toks, md_tps, md_snap = run_multi_probe(K)
    tpl = md_snap.get("decode_tokens_per_launch", 0)
    print(f"MULTI_DECODE_CHIP K={K} tok_s={md_tps:.1f} "
          f"speedup={md_tps / md_base_tps:.2f}x "
          f"tokens_per_launch={tpl} "
          f"tpot_p50_ms={md_snap.get('tpot_p50_ms')} "
          f"launches={md_snap.get('decode_launches')}")
    # full batch, uniform lengths, no EOS: every row emits its cap
    # each launch — the >= 0.9 K acceptance number is host-exact
    assert tpl >= 0.9 * K, (K, tpl)
    if ON_TPU:
        assert md_toks == md_base, f"K={K} changed greedy tokens"
    elif md_toks != md_base:
        m = sum(a == b for bo, so in zip(md_base, md_toks)
                for a, b in zip(bo, so))
        t = sum(len(v) for v in md_base)
        print(f"MULTI_DECODE_CPU_REPORT_ONLY K={K} match={m}/{t} "
              "(hard gate runs on TPU)")
print("MULTI_DECODE_CHIP_OK")

# --- tensor-parallel serving probe (ISSUE 8) ---------------------------
# TP in {1, 2, 4} engines over the hybrid mesh's 'model' axis at FIXED
# model size: tok/s and per-chip KV GB/s (global engine-accounted bytes
# / tp / wall — bytes-true through paged_page_bytes), plus the page-
# capacity multiplier at a fixed per-chip pool budget. Timing is
# fetch-synced by construction (every step() host-fetches the sampled
# tokens — the only honest sync over the axon relay, CLAUDE.md timing
# landmine #1). Degrees are clamped to the devices actually present —
# a single-chip grant probes TP=1 only and says so. Greedy token
# identity across degrees is a CHIP gate (ON_TPU, same rationale as
# the eager-vs-jit gate above: TP changes reduction layouts, and CPU
# near-tie bf16 rounding is report-only off chip); staged chip-blind —
# the CPU contract is pinned by tests/test_serving_tp.py in f32.
from paddle_tpu.serving import tp_serving_mesh

TP_PROMPTS = [rng.randint(0, cfg.vocab_size, (12,)).tolist()
              for _ in range(8)]
tp_degrees = [t for t in (1, 2, 4)
              if t <= len(jax.devices())
              and cfg.num_key_value_heads % t == 0]
if tp_degrees[1:]:
    tp_outs = {}
    for tp in tp_degrees:
        import paddle_tpu as _p
        _p.seed(0)
        tmodel = LlamaForCausalLM(cfg)
        tmodel.bfloat16()
        eng = ServingEngine(tmodel, num_pages=128, page_size=16,
                            batch_buckets=[8], prefill_buckets=[16, 128],
                            pages_buckets=[8], temperature=0.0,
                            mesh=tp_serving_mesh(tp) if tp > 1 else None)
        t0 = time.perf_counter()
        rids = [eng.add_request(p, max_new_tokens=32) for p in TP_PROMPTS]
        out = eng.run()
        wall = time.perf_counter() - t0
        snap = eng.metrics.snapshot()
        tp_outs[tp] = [out[r] for r in rids]
        toks = sum(len(t) for t in tp_outs[tp])
        kv_gb = (snap["kv_bytes_read"] + snap["kv_bytes_written"]) / 1e9
        print(f"TP_SERVING_CHIP tp={tp} tok_s={toks / wall:.1f} "
              f"per_chip_kv_gbps={kv_gb / tp / wall:.2f} "
              f"page_bytes_shard={snap['kv_page_bytes_shard']}")
        assert eng.num_compiled_programs <= eng.max_program_count()
        eng.reset_prefix_cache()
        assert eng.allocator.num_used == 0
        eng.shutdown()
        if tp > 1:
            if ON_TPU:
                assert tp_outs[tp] == tp_outs[1], \
                    f"TP={tp} changed greedy tokens"
            elif tp_outs[tp] != tp_outs[1]:
                m = sum(a == b for bo, so in zip(tp_outs[1], tp_outs[tp])
                        for a, b in zip(bo, so))
                t = sum(len(v) for v in tp_outs[1])
                print(f"TP_CPU_REPORT_ONLY tp={tp} match={m}/{t} "
                      "(hard gate runs on TPU)")
    # per-chip capacity multiplier at a fixed pool budget (pure
    # geometry through paged_page_bytes — asserted anywhere)
    pb1 = paged_page_bytes(cfg.num_key_value_heads, 16,
                           cfg.hidden_size // cfg.num_attention_heads,
                           "bfloat16")
    tp_hi = tp_degrees[-1]
    pb_shard = paged_page_bytes(cfg.num_key_value_heads // tp_hi, 16,
                                cfg.hidden_size // cfg.num_attention_heads,
                                "bfloat16")
    POOL = 64 << 20
    print(f"TP page capacity at {POOL >> 20} MB/chip: tp1 {POOL // pb1} "
          f"tp{tp_hi} {POOL // pb_shard} "
          f"({(POOL // pb_shard) / (POOL // pb1):.2f}x)")
    assert POOL // pb_shard >= tp_hi * (POOL // pb1)
    print("TP_SERVING_CHIP_OK")
else:
    print(f"TP_SERVING_CHIP_SKIPPED: {len(jax.devices())} device(s) — "
          "single-chip grant; TP probe needs a multi-chip window")

# --- multi-LoRA serving probe (ISSUE 15) -------------------------------
# N-adapter tok/s vs the single-adapter baseline over the same
# 8-request workload: every decode launch mixes adapters (the masked
# segment-bmm streams each loaded adapter's A/B once per launch), so
# the ladder measures what serving N adapters costs over serving one —
# the >= 0.7x acceptance bar. Timing is fetch-synced by construction
# (step() host-fetches tokens). Per-adapter identity vs a solo engine
# is a CHIP gate (ON_TPU — this probe's model is bf16 and CPU rounds
# near-tie logits differently; the f32 CPU identity contract is pinned
# by tests/test_serving_lora.py).
from paddle_tpu.serving import AdapterRegistry, LoRAAdapter
from paddle_tpu.serving.lora.store import llama_lora_dims

LORA_DIMS = llama_lora_dims(cfg)
LORA_PROMPTS = [rng.randint(0, cfg.vocab_size, (12,)).tolist()
                for _ in range(8)]


def _lora_adapter(i):
    return LoRAAdapter.random(f"ad{i}", 8, LORA_DIMS, seed=500 + i)


def run_lora_probe(n_adapters):
    import paddle_tpu as _p
    _p.seed(0)
    lmodel = LlamaForCausalLM(cfg)
    lmodel.bfloat16()
    reg = AdapterRegistry(LORA_DIMS, rank_buckets=(8,),
                          slots=max(2, n_adapters + 1))
    for i in range(n_adapters):
        reg.load(_lora_adapter(i))
    eng = ServingEngine(lmodel, lora=reg, num_pages=256, page_size=16,
                        batch_buckets=[8], prefill_buckets=[16, 128],
                        pages_buckets=[8], temperature=0.0)
    t0 = time.perf_counter()
    rids = [eng.add_request(p, max_new_tokens=32,
                            adapter=f"ad{j % n_adapters}")
            for j, p in enumerate(LORA_PROMPTS)]
    out = eng.run()
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    assert eng.num_compiled_programs <= eng.max_program_count()
    eng.reset_prefix_cache()
    assert eng.allocator.num_used == 0
    reg.check_invariants()
    eng.shutdown()
    toks = {j: out[r] for j, r in enumerate(rids)}
    return toks, sum(len(t) for t in toks.values()) / wall, snap


lora_outs, lora_base_tps, _ = run_lora_probe(1)
print(f"lora baseline: 1 adapter {lora_base_tps:.1f} tok/s")
lora_na_outs = {}
for NA in (4, 8):
    la_outs, la_tps, la_snap = run_lora_probe(NA)
    lora_na_outs[NA] = la_outs
    print(f"LORA_CHIP n_adapters={NA} tok_s={la_tps:.1f} "
          f"vs_solo={100 * la_tps / lora_base_tps:.1f}% "
          f"adapter_mix_p50={la_snap.get('adapter_mix_p50')} "
          f"loaded={la_snap.get('adapters_loaded')}")
    if ON_TPU:
        # the >= 0.7x acceptance bar is a CHIP number (off-relay CPU
        # wall times are harness evidence only)
        assert la_tps >= 0.7 * lora_base_tps, (la_tps, lora_base_tps)

# per-adapter identity: mixed engine rows == a solo engine running the
# SAME rows with only that adapter loaded (hard gate ON_TPU only)
import paddle_tpu as _p
_p.seed(0)
_solo_model = LlamaForCausalLM(cfg)
_solo_model.bfloat16()
_solo_reg = AdapterRegistry(LORA_DIMS, rank_buckets=(8,), slots=2)
_solo_reg.load(_lora_adapter(0))
_solo_eng = ServingEngine(_solo_model, lora=_solo_reg, num_pages=256,
                          page_size=16, batch_buckets=[8],
                          prefill_buckets=[16, 128], pages_buckets=[8],
                          temperature=0.0)
_mix4 = lora_na_outs[4]
_solo_rids = [_solo_eng.add_request(p, max_new_tokens=32, adapter="ad0")
              for j, p in enumerate(LORA_PROMPTS) if j % 4 == 0]
_solo_out = _solo_eng.run()
_solo_eng.shutdown()
solo_toks = [_solo_out[r] for r in _solo_rids]
mix_toks = [_mix4[j] for j in range(len(LORA_PROMPTS)) if j % 4 == 0]
if ON_TPU:
    assert solo_toks == mix_toks, "mixed engine changed adapter-0 tokens"
    print("LORA_IDENTITY_CHIP_OK")
elif solo_toks != mix_toks:
    m = sum(a == b for so, mo in zip(solo_toks, mix_toks)
            for a, b in zip(so, mo))
    t = sum(len(v) for v in solo_toks)
    print(f"LORA_CPU_REPORT_ONLY match={m}/{t} (hard gate runs on TPU)")
print("LORA_CHIP_OK")

# --- tiered-KV spill probe (ISSUE 17) ----------------------------------
# Cached-token rate at a tiny FORCED-SPILL device pool vs the same pool
# HBM-only: 24 queued requests round-robin 4 distinct 64-token (4-page)
# prefixes against a 22-page device pool, so the radix tree cannot hold
# all 16 prefix pages on device alongside the live batch — HBM-only
# drops the LRU prefix and recomputes it, the spill tier demotes it to
# host RAM and promotes it back on the next hit (promotion needs free
# device pages AT match time, which is why the requests run as one
# continuously-batched queue: duplicate-span donations from completing
# cache-hit rows return their shared pages to the free list mid-run —
# the sequential one-at-a-time shape starves promotion by design).
# Bit-identity spill-on vs spill-off is a HARD gate everywhere (not
# just ON_TPU): promotion restores the exact bytes the prefill wrote,
# and spill on/off cannot change program shapes, so there is no
# legitimate divergence source on any backend (the CPU contract is
# pinned by tests/test_serving_spill.py). The cached-token counters
# are host-exact bookkeeping and assert anywhere; wall-clock is
# printed, not asserted (chip variance stays out of the gate). On chip
# this is the first time the promotion host->device copy runs over the
# real relay.
from paddle_tpu.utils import faults

spill_rng = np.random.RandomState(17)
SPILL_SHARED = [spill_rng.randint(0, cfg.vocab_size, (64,)).tolist()
                for _ in range(4)]
SPILL_TAILS = [spill_rng.randint(0, cfg.vocab_size, (8,)).tolist()
               for _ in range(24)]


def run_spill_probe(host_pages):
    eng = ServingEngine(model, num_pages=22, page_size=16,
                        batch_buckets=[4], prefill_buckets=[128],
                        pages_buckets=[8], temperature=0.0,
                        host_spill_pages=host_pages)
    t0 = time.perf_counter()
    rids = [eng.add_request(SPILL_SHARED[i % 4] + tail,
                            max_new_tokens=16)
            for i, tail in enumerate(SPILL_TAILS)]
    out = eng.run()
    outs = [out[r] for r in rids]
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    eng.reset_prefix_cache()
    assert eng.allocator.num_used == 0
    if eng.host_store is not None:
        assert eng.host_store.num_used == 0          # both pools reclaim
        eng.host_store.check_invariants()
    assert eng.num_compiled_programs <= eng.max_program_count()
    eng.shutdown()
    return outs, wall, snap


sp_off, sp_off_wall, sp_off_snap = run_spill_probe(0)
sp_on, sp_on_wall, sp_on_snap = run_spill_probe(32)
print(f"TIERED_KV_CHIP off: wall {sp_off_wall:.3f}s "
      f"cached_tokens {sp_off_snap['cached_tokens_served']} "
      f"| on: wall {sp_on_wall:.3f}s "
      f"cached_tokens {sp_on_snap['cached_tokens_served']} "
      f"demoted {sp_on_snap['kv_pages_demoted']} "
      f"promoted {sp_on_snap['kv_pages_promoted']} "
      f"host_hits {sp_on_snap['host_prefix_hits']}")
assert sp_on == sp_off, "spill tier changed greedy tokens"
assert sp_on_snap["kv_pages_demoted"] > 0
assert sp_on_snap["kv_pages_promoted"] > 0
assert sp_on_snap["host_prefix_hits"] >= 1
# the acceptance number: cached-token rate ABOVE the HBM-only ceiling
# at FIXED device-pool bytes
assert sp_on_snap["cached_tokens_served"] > \
    sp_off_snap["cached_tokens_served"], (sp_on_snap, sp_off_snap)

# fault degrade on the real promotion path: one corrupt host page must
# fall back to recompute-from-radix-prefix with identical tokens
faults.inject("host_spill.corrupt", payload=True, after=1, times=1)
try:
    sp_chaos, _, sp_chaos_snap = run_spill_probe(32)
    assert faults.fired_counts().get("host_spill.corrupt", 0) >= 1
finally:
    faults.clear()
    faults.reset_counts()
assert sp_chaos == sp_off, "corrupt-page recompute changed greedy tokens"
assert sp_chaos_snap["host_spill_corrupt"] >= 1
print(f"TIERED_KV_CHIP_OK cached_on={sp_on_snap['cached_tokens_served']} "
      f"cached_off={sp_off_snap['cached_tokens_served']} "
      f"corrupt_recomputes={sp_chaos_snap['host_spill_corrupt']}")

# --- disaggregated prefill/decode probe (ISSUE 18) ---------------------
# The handoff round trip ON the real chip, in ONE process (the chip's
# single-process rule forbids spawning role workers here, so this
# drives the same engine-level machinery the fleet supervisor
# orchestrates): a prefill-role engine runs admission + chunked
# prefill + first token and finishes "handoff"; the donated prefix
# exports, rides the real chunk/join payload codec (FRAME_CAP
# chunking, CRC per page), and a SECOND engine adopts the pages and
# streams the rest. Bit-identity vs the co-located engine is a HARD
# gate everywhere (single-bucket grid + greedy: the adopted
# continuation replays the preemption-resume path); on chip this is
# the first time the exported page bytes round-trip through device
# fetch + host re-upload over the real relay.
from paddle_tpu.serving.fleet.transport import (chunk_payloads,
                                                join_payloads)

DG_KW = dict(num_pages=48, page_size=16, token_budget=64,
             batch_buckets=[8], prefill_buckets=[64], pages_buckets=[8],
             temperature=0.0)
dg_rng = np.random.RandomState(18)
DG_WORK = [(dg_rng.randint(0, cfg.vocab_size, (dg_rng.randint(32, 48),))
            .tolist(), 12) for _ in range(8)]

dg_ref_eng = ServingEngine(model, **DG_KW)
dg_ref_rids = [dg_ref_eng.add_request(p, max_new_tokens=m)
               for p, m in DG_WORK]
dg_t0 = time.perf_counter()
dg_ref = dg_ref_eng.run()
dg_coloc_wall = time.perf_counter() - dg_t0
dg_ref_eng.shutdown()

dg_pre = ServingEngine(model, role="prefill", **DG_KW)
dg_dec = ServingEngine(model, **DG_KW)
dg_t0 = time.perf_counter()
dg_rids = [dg_pre.add_request(p, max_new_tokens=m) for p, m in DG_WORK]
while dg_pre.has_work():
    dg_pre.step()
dg_shipped = 0
dg_recs = []
for (p, m), rid in zip(DG_WORK, dg_rids):
    req = dg_pre.requests[rid]
    assert req.finish_reason == "handoff", req.finish_reason
    toks = (p + list(req.output_ids))[:req.handoff_prefix_len]
    n, payloads = dg_pre.export_prefix(toks)
    assert n == req.handoff_prefix_len, (n, req.handoff_prefix_len)
    adopted = dg_dec.adopt_prefix(
        toks[:n], join_payloads(chunk_payloads(payloads)))
    assert adopted == len(payloads), (adopted, len(payloads))
    dg_shipped += adopted
    dg_pre.release_prefix(toks[:n])
    dg_recs.append({"request_id": rid, "prompt_ids": p,
                    "output_ids": list(req.output_ids),
                    "max_new_tokens": m, "eos_token_id": None,
                    "num_preemptions": 0, "aborted": False,
                    "adapter": None, "colocate": False,
                    "deadline_remaining_s": None})
dg_dec.adopt_requests(dg_recs)
dg_out = dg_dec.run()
dg_wall = time.perf_counter() - dg_t0
# adopted records fold the pre-handoff tokens back in, so the decode
# engine's output IS the full stream
assert [dg_out[r] for r in dg_rids] == \
    [dg_ref[r] for r in dg_ref_rids], \
    "disaggregated handoff changed greedy tokens"
assert dg_pre.metrics.counters["prefill_handoffs"] == len(DG_WORK)
assert dg_dec.metrics.counters["kv_pages_adopted"] == dg_shipped
for e in (dg_pre, dg_dec):
    e.reset_prefix_cache()
    assert e.allocator.num_used == 0
    e.shutdown()
print(f"DISAGG_CHIP_OK pages_shipped={dg_shipped} "
      f"handoffs={len(DG_WORK)} coloc_wall={dg_coloc_wall:.3f}s "
      f"disagg_wall={dg_wall:.3f}s")

print("CHIP_SERVING_ALL_OK")
