#!/bin/sh
# Cheap axon-relay liveness probe. The grant-claim leg dials
# 127.0.0.1:8082 (axon/register/ifrt.py ":8082 claim"); when nothing
# listens there jax.devices() blocks forever. Run this before TPU work:
#   sh tools/relay_check.sh && <tpu command>
# Exit 0 = a listener exists on the claim port range (relay likely up).
ss -tln 2>/dev/null | grep -qE ':(808[2-9]|809[0-9]|810[0-9]|811[0-7]) '
