"""Merge per-rank pipeline timeline exports into one distributed report.

Cross-process launched jobs (distributed.launch) each write their own
view of the run under $PADDLE_TPU_PROFILER_DIR —
`ThreadedFleetExecutor.export_rank_timelines()` /
`ThreadedZBVExecutor.export_rank_timelines()` produce one
`pipeline_rank<N>.json` chrome-trace per rank, carrying the F/B/W job
spans, the measured-vs-simulated bubble digest, and (optionally) the
program's collective accounting (`TracedFunction.comm_report()`). This
tool merges them into ONE rank-labelled chrome trace (load it in
Perfetto / chrome://tracing) and prints the digest:

* per-rank span counts, busy time and per-kind durations;
* the pipeline bubble table (measured vs `simulate_pipeline_makespan`
  fractions, straight from each export's `pipeline` section);
* the collective-traffic digest (payload bytes per mesh axis; ranks of
  one SPMD program account identical bytes — the digest reports the
  per-rank value and flags disagreement instead of summing it 8x).

Deliberately stdlib-only: loading this module must never import jax
(every plain `python` start claims the TPU grant — CLAUDE.md), so the
report runs anywhere, including while a launched fleet holds the chip.
`--demo` is the one exception: it lazily imports paddle_tpu to run a
tiny threaded ZB pipeline and write real per-rank exports first.

Usage:  python tools/dist_report.py [DIR] [--out MERGED.json]
        python tools/dist_report.py --demo [DIR]
(`make dist-report` runs the demo + merge as a smoke.)
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional


def rank_files(log_dir: str) -> List[str]:
    """The per-rank exports under `log_dir`, rank-sorted."""
    paths = glob.glob(os.path.join(log_dir, "pipeline_rank*.json"))

    def rank_of(p):
        stem = os.path.basename(p)[len("pipeline_rank"):-len(".json")]
        return int(stem) if stem.isdigit() else 1 << 30
    return sorted(paths, key=rank_of)


def load_docs(paths: List[str]) -> List[dict]:
    docs = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        doc.setdefault("rank", len(docs))
        docs.append(doc)
    return docs


def merge_trace(docs: List[dict]) -> dict:
    """One chrome-trace document over every rank's export: span events
    re-labelled tid=GLOBAL rank (the per-rank files of one process
    carry local tids), one thread_name row per rank. Spans were stamped
    on each host's perf_counter — within one host they share a base and
    the merged view is exact; exports carrying more than one distinct
    `host` stamp get a `hosts` list here and a WARNING in the digest
    (per-host clock bases differ; alignment would be fiction)."""
    events: List[dict] = []
    pids = set()
    for doc in docs:
        rank = int(doc.get("rank", 0))
        for e in doc.get("traceEvents", ()):
            if e.get("ph") != "X":
                pids.add(e.get("pid"))
                continue
            ev = dict(e)
            ev["tid"] = rank
            events.append(ev)
    pid = next((p for p in pids if p is not None), 3)
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": "pipeline ranks (merged)"}}]
    for doc in docs:
        rank = int(doc.get("rank", 0))
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": rank, "args": {"name": f"rank {rank}"}})
    for ev in events:
        ev["pid"] = pid
    merged = {"displayTimeUnit": "ms",
              "traceEvents": meta + sorted(events,
                                           key=lambda e: e["ts"]),
              "ranks": [int(d.get("rank", 0)) for d in docs]}
    hosts = sorted({str(d["host"]) for d in docs if d.get("host")})
    if hosts:
        merged["hosts"] = hosts
    pipelines = [d["pipeline"] for d in docs if "pipeline" in d]
    if pipelines:
        merged["pipeline"] = pipelines[0]
    comms = [d["comm"] for d in docs if "comm" in d]
    if comms:
        merged["comm"] = comms[0]
    return merged


# ---------------------------------------------------------------- digest
def format_rank_table(docs: List[dict]) -> str:
    lines = [f"{'rank':>4}{'spans':>8}{'busy(ms)':>12}{'F':>6}{'B':>6}"
             f"{'W':>6}"]
    lines.append("-" * len(lines[0]))
    for doc in docs:
        spans = [e for e in doc.get("traceEvents", ())
                 if e.get("ph") == "X"]
        busy = sum(e["dur"] for e in spans) / 1e3
        kinds = {"F": 0, "B": 0, "W": 0}
        for e in spans:
            k = e.get("args", {}).get("kind", e.get("name", "?")[:1])
            if k in kinds:
                kinds[k] += 1
        lines.append(f"{doc.get('rank', '?'):>4}{len(spans):>8}"
                     f"{busy:>12.3f}{kinds['F']:>6}{kinds['B']:>6}"
                     f"{kinds['W']:>6}")
    return "\n".join(lines)


def format_bubble(docs: List[dict]) -> str:
    pipes = [d["pipeline"] for d in docs if "pipeline" in d]
    if not pipes:
        return "(no pipeline digest in exports)"
    p = pipes[0]   # every rank file of one run carries the same digest
    lines = [f"schedule {p.get('schedule')}: workers={p.get('workers')} "
             f"jobs={p.get('jobs')}"]
    mk, sim = p.get("makespan_s"), p.get("sim_makespan_s")
    if mk is not None:
        lines.append(f"  measured makespan {mk * 1e3:10.3f} ms   "
                     f"bubble {p.get('bubble_fraction'):.4f}"
                     if p.get("bubble_fraction") is not None
                     else f"  measured makespan {mk * 1e3:10.3f} ms")
    if sim is not None:
        lines.append(f"  modeled  makespan {sim * 1e3:10.3f} ms   "
                     f"bubble {p.get('sim_bubble_fraction'):.4f}  "
                     f"(simulate_pipeline_makespan on measured "
                     f"durations)")
    return "\n".join(lines)


def format_comm(docs: List[dict]) -> str:
    comms = [(int(d.get("rank", 0)), d["comm"]) for d in docs
             if isinstance(d.get("comm"), dict)]
    if not comms:
        return "(no comm accounting in exports)"
    lines = []
    # one SPMD program: every rank should account the SAME bytes
    base = json.dumps(comms[0][1].get("bytes_per_axis"), sort_keys=True)
    agree = all(json.dumps(c.get("bytes_per_axis"), sort_keys=True)
                == base for _, c in comms)
    rank, c = comms[0]
    lines.append(f"payload bytes {c.get('payload_bytes')} "
                 f"per axis {c.get('bytes_per_axis')} "
                 f"ops {c.get('op_counts')}")
    if agree:
        lines.append(f"  ({len(comms)} rank exports agree — one SPMD "
                     f"program, bytes reported once, not summed)")
    else:
        lines.append("  WARNING: rank exports DISAGREE on bytes_per_axis"
                     " (heterogeneous programs?):")
        for rank, c in comms:
            lines.append(f"    rank {rank}: {c.get('bytes_per_axis')}")
    return "\n".join(lines)


def report(docs: List[dict]) -> str:
    parts = []
    hosts = sorted({str(d["host"]) for d in docs if d.get("host")})
    if len(hosts) > 1:
        parts += [f"WARNING: exports span {len(hosts)} hosts "
                  f"({', '.join(hosts)}) — perf_counter bases are "
                  f"per-host, cross-host span alignment in the merged "
                  f"trace is not meaningful", ""]
    parts += ["== per-rank spans ==", format_rank_table(docs), "",
              "== pipeline bubbles ==", format_bubble(docs), "",
              "== collective traffic ==", format_comm(docs)]
    return "\n".join(parts)


# ------------------------------------------------------------------ demo
def run_demo(log_dir: str) -> None:
    """Run a tiny threaded ZB-H1 pipeline and write real per-rank
    exports (with a live comm_report) under `log_dir`. The ONLY
    jax-importing entry point of this file (opt-in via --demo; the
    reporting paths above stay stdlib-only by contract). Stale
    pipeline_rank*.json from earlier runs are cleared first — merging
    exports from two different runs (different clock epochs, possibly
    different rank counts) would produce a chimera digest."""
    import time

    for stale in rank_files(log_dir):
        try:
            os.remove(stale)
        except OSError:
            pass

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # the comm side of the demo needs a multi-device mesh: force the
    # 8-device virtual CPU platform BEFORE jax initializes (the tests'
    # conftest rule) — on one device the honest accounting is 0 bytes
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=8"
    import numpy as np

    from paddle_tpu.distributed.fleet_executor import ThreadedFleetExecutor

    def fwd(r, m, x):
        time.sleep(0.002)
        return x

    def bwd(r, m, g):
        time.sleep(0.002)
        return g

    def w(r, m):
        time.sleep(0.001)

    ex = ThreadedFleetExecutor(2, 4, "ZB-H1", fwd, bwd, w)
    ex.run(list(range(4)), list(range(4)))

    # a real compiled-program comm accounting to ride the export: the
    # demo matmul psums its loss over the full 8-device mesh
    comm = None
    try:
        import jax
        from paddle_tpu.profiler import comm as _comm
        from paddle_tpu.distributed.fleet import fleet, DistributedStrategy
        st = DistributedStrategy()
        st.hybrid_configs = {"dp_degree": max(len(jax.devices()) // 2, 1),
                             "mp_degree": 2 if len(jax.devices()) >= 2
                             else 1, "pp_degree": 1, "sharding_degree": 1,
                             "sep_degree": 1}
        fleet._hcg = None
        fleet.init(is_collective=True, strategy=st)
        mesh = fleet.get_hybrid_communicate_group().mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        def loss(a):
            a = jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P("data", "model")))
            return a.sum()

        comm = _comm.jit_comm(
            loss, jax.ShapeDtypeStruct((8, 16), np.float32),
            mesh=mesh).to_dict()
    except Exception as e:                                 # noqa: BLE001
        print(f"(demo comm accounting unavailable: {e})")
    paths = ex.export_rank_timelines(log_dir, comm=comm)
    print(f"demo pipeline exports written: {paths}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", nargs="?", default=None,
                    help="directory of pipeline_rank*.json exports "
                         "(default: $PADDLE_TPU_PROFILER_DIR or "
                         "./profiler_log)")
    ap.add_argument("--out", default=None,
                    help="write the merged chrome trace here")
    ap.add_argument("--demo", action="store_true",
                    help="first run a tiny threaded pipeline and write "
                         "per-rank exports (imports paddle_tpu)")
    args = ap.parse_args(argv)
    log_dir = args.dir or os.environ.get("PADDLE_TPU_PROFILER_DIR") \
        or "./profiler_log"
    if args.demo:
        run_demo(log_dir)
    paths = rank_files(log_dir)
    if not paths:
        print(f"no pipeline_rank*.json exports under {log_dir}")
        return 1
    docs = load_docs(paths)
    print(f"merging {len(paths)} rank exports from {log_dir}")
    print(report(docs))
    if args.out:
        merged = merge_trace(docs)
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"merged chrome trace written to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
