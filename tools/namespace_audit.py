"""Audit every reference __all__ list against the live paddle_tpu surface.

Usage: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/namespace_audit.py

Walks /root/reference/python/paddle for files with __all__, resolves the
same module path on paddle_tpu, and reports missing names / modules.
Known-excluded subsystems (SURVEY A.7) are filtered to keep the report
actionable.
"""
import os
import re
import sys

REF = "/root/reference/python/paddle"

EXCLUDED_PREFIXES = (
    "cinn", "tensorrt", "device.xpu", "incubate.xpu",
    "distributed.ps", "autograd.ir_backward", "cost_model",
    "incubate.distributed.fleet.fleet_util",
    # the package re-export shadows the module attribute in the REFERENCE
    # too (paddle.text.viterbi_decode is the function there as well, so
    # this attribute walk fails identically on the reference); the module
    # file exists with matching __all__ at paddle_tpu/text/viterbi_decode.py
    "text.viterbi_decode",
)


def ref_all(path):
    src = open(path, errors="ignore").read()
    i = src.find("__all__")
    if i < 0:
        return []
    j = src.find("]", i)
    return re.findall(r"['\"]([A-Za-z0-9_]+)['\"]", src[i:j])


def main():
    import paddle_tpu as paddle
    mods = []
    for root, dirs, files in os.walk(REF):
        dirs[:] = [d for d in dirs
                   if d not in ("tests", "__pycache__", "libs", "include")]
        for f in files:
            p = os.path.join(root, f)
            if f == "__init__.py" or (
                    f.endswith(".py")
                    and "__all__" in open(p, errors="ignore").read()[:5000]):
                mods.append(p)
    report = []
    for path in mods:
        rel = os.path.relpath(path, REF)
        modpath = rel[:-3].replace("/__init__", "").replace("/", ".")
        if modpath in ("", "__init__"):
            continue
        if any(modpath.startswith(e) for e in EXCLUDED_PREFIXES):
            continue
        names = ref_all(path)
        if not names:
            continue
        obj = paddle
        ok = True
        for part in modpath.split("."):
            if not hasattr(obj, part):
                ok = False
                break
            obj = getattr(obj, part)
        if not ok:
            report.append(f"{modpath}: MODULE MISSING ({len(names)} names)")
            continue
        missing = [n for n in dict.fromkeys(names) if not hasattr(obj, n)]
        if missing:
            report.append(f"{modpath}: missing {missing}")
    for line in sorted(report):
        print(line)
    print(f"\n{len(report)} modules with gaps (excluded: "
          f"{', '.join(EXCLUDED_PREFIXES)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
