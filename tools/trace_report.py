"""Offline report over an exported serving trace / flight recorder.

Reads the JSON `RequestTracer.export()` writes (chrome `traceEvents`
plus the `requestTraces` / `flightRecorder` side-channels) — or a bare
engine snapshot / `engine.timeline()` dump carrying only a flight
recorder — and prints:

* a per-phase latency table (queue_wait / prefill_chunk / decode_step /
  verify_step / migration park->adopt / total request lifetime, with
  count, p50, p99, total);
* the slowest requests' span-by-span breakdown;
* a flight-recorder digest (step latency percentiles, occupancy range,
  program-launch counts per family, fault/retry totals).

Deliberately stdlib-only: loading this module must never import jax
(every plain `python` start claims the TPU grant — CLAUDE.md), so the
report runs anywhere, including while an engine holds the chip.

Usage:  python tools/trace_report.py TRACE.json [--slowest 3]
(`make soak` runs it over the soak's exported trace as a smoke.)
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

# phases reported in table order; "migration" and "total" are derived
PHASES = ("queue_wait", "prefill_chunk", "decode_step", "verify_step")


def _percentile(samples, q):
    """Nearest-rank percentile (the serving.metrics rule, duplicated so
    this tool stays import-free)."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def load(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        # a bare engine.timeline() dump
        return {"flightRecorder": data}
    if "flight_recorder" in data and "requestTraces" not in data:
        # an engine snapshot: the recorder rides under its snapshot key
        return {"flightRecorder": data["flight_recorder"]}
    return data


# --------------------------------------------------------------- phases
def phase_durations_ms(traces: List[dict]) -> Dict[str, List[float]]:
    """{phase: [durations ms]} over every request trace, including the
    derived `migration` (park -> adopt gap) and `total` phases."""
    out: Dict[str, List[float]] = {p: [] for p in PHASES}
    out["migration"] = []
    out["total"] = []
    for tr in traces:
        for s in tr.get("spans", ()):
            out.setdefault(s["name"], []).append(
                (s["t1"] - s["t0"]) / 1e6)
        park = None
        for m in tr.get("marks", ()):
            if m["name"] == "park":
                park = m["t"]
            elif m["name"] == "adopt" and park is not None:
                out["migration"].append((m["t"] - park) / 1e6)
                park = None
        if tr.get("t_end") is not None:
            out["total"].append((tr["t_end"] - tr["t_begin"]) / 1e6)
    return out


def format_phase_table(traces: List[dict]) -> str:
    durs = phase_durations_ms(traces)
    lines = [f"{'phase':<16}{'count':>8}{'p50(ms)':>12}{'p99(ms)':>12}"
             f"{'total(ms)':>12}"]
    lines.append("-" * len(lines[0]))
    order = list(PHASES) + ["migration", "total"]
    order += sorted(k for k in durs if k not in order)
    for phase in order:
        samples = durs.get(phase, ())
        if not samples:
            continue
        lines.append(
            f"{phase:<16}{len(samples):>8}"
            f"{_percentile(samples, 50):>12.3f}"
            f"{_percentile(samples, 99):>12.3f}"
            f"{sum(samples):>12.3f}")
    return "\n".join(lines)


def format_slowest(traces: List[dict], n: int = 3) -> str:
    done = [t for t in traces if t.get("t_end") is not None]
    done.sort(key=lambda t: t["t_end"] - t["t_begin"], reverse=True)
    lines = []
    for tr in done[:n]:
        total = (tr["t_end"] - tr["t_begin"]) / 1e6
        lines.append(f"request {tr['request_id']} "
                     f"({tr.get('finish_reason')}): {total:.3f} ms, "
                     f"{len(tr.get('spans', ()))} spans")
        by_name: Dict[str, List[float]] = {}
        for s in tr.get("spans", ()):
            by_name.setdefault(s["name"], []).append(
                (s["t1"] - s["t0"]) / 1e6)
        for name, ds in sorted(by_name.items(),
                               key=lambda kv: -sum(kv[1])):
            lines.append(f"    {name:<16} x{len(ds):<4} "
                         f"total {sum(ds):10.3f} ms  "
                         f"max {max(ds):8.3f} ms")
        marks = [m["name"] for m in tr.get("marks", ())]
        if marks:
            lines.append(f"    marks: {' '.join(marks)}")
    return "\n".join(lines) if lines else "(no completed traces)"


# ------------------------------------------------------ flight recorder
def format_flight_recorder(records: List[dict]) -> str:
    if not records:
        return "(empty flight recorder)"
    lat = [r["t_wall_ms"] for r in records
           if isinstance(r.get("t_wall_ms"), (int, float))]
    occ = [r["kv_occupancy"] for r in records if "kv_occupancy" in r]
    fams: Dict[str, int] = {}
    for r in records:
        for p in r.get("programs", ()):
            fam = str(p).split(":", 1)[0]
            fams[fam] = fams.get(fam, 0) + 1
    totals = {k: sum(int(r.get(k, 0) or 0) for r in records)
              for k in ("tokens_out", "prefill_tokens", "retries",
                        "quarantined", "preempted", "prefix_hits",
                        "spec_drafted", "spec_accepted")}
    lines = [f"flight recorder: {len(records)} steps "
             f"(#{records[0].get('step')}..#{records[-1].get('step')})"]
    if lat:
        lines.append(
            f"  step latency ms: p50 {_percentile(lat, 50):.3f}  "
            f"p99 {_percentile(lat, 99):.3f}  max {max(lat):.3f}")
    if occ:
        lines.append(f"  kv occupancy: min {min(occ):.4f}  "
                     f"max {max(occ):.4f}")
    lines.append("  launches: " + (" ".join(
        f"{k}={v}" for k, v in sorted(fams.items())) or "(none)"))
    lines.append("  totals:   " + " ".join(
        f"{k}={v}" for k, v in totals.items() if v))
    failed = [r for r in records if r.get("failed")]
    for r in failed:
        lines.append(f"  FAILED step #{r.get('step')}: {r['failed']}")
    return "\n".join(lines)


def report(data: dict, slowest: int = 3) -> str:
    """Compose every section the document carries."""
    parts = []
    traces = data.get("requestTraces")
    if traces:
        parts.append("== per-phase latency ==")
        parts.append(format_phase_table(traces))
        parts.append("")
        parts.append(f"== slowest {slowest} requests ==")
        parts.append(format_slowest(traces, slowest))
    recs = data.get("flightRecorder")
    if recs:
        if parts:
            parts.append("")
        parts.append("== engine flight recorder ==")
        parts.append(format_flight_recorder(recs))
    if not parts:
        parts.append("(no requestTraces or flightRecorder in input)")
    return "\n".join(parts)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="exported trace / flight recorder JSON")
    ap.add_argument("--slowest", type=int, default=3,
                    help="how many slowest requests to break down")
    args = ap.parse_args(argv)
    print(report(load(args.path), slowest=args.slowest))
    return 0


if __name__ == "__main__":
    sys.exit(main())
