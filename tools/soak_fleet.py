"""Multi-replica chaos soak for the fleet front-end (ISSUE 7).

Runs the SAME seeded shared-prefix-heavy workload four times on CPU:

* `single`  — one replica, prefix-affinity router, no faults: the
  PR-2-style single-replica radix baseline the routing criterion is
  measured against;
* `clean`   — three replicas, prefix-affinity router, no faults: the
  reference token streams;
* `chaos`   — three replicas, prefix-affinity router, with a seeded
  KILL of replica-0 mid-stream (`fleet.replica_crash`), a permanent
  STALL of replica-1 (`fleet.stream_stall` -> stall detector), routing
  races, injected allocator OOM, and transient step errors;
* `random`  — three replicas, seeded RandomRouter, no faults: the
  routing-criterion strawman.

Acceptance assertions (ISSUE 7):

* zero-loss failover: EVERY accepted request completes in the chaos
  pass, with its token stream BIT-IDENTICAL to the clean pass (zero
  lost requests, zero duplicated or reordered tokens — migration
  preserves tokens-so-far and greedy continuation is deterministic
  under the pinned bucket grid);
* full page/refcount reclamation on every replica's pool — including
  the killed and the stalled one (vacate at evacuation);
* prefix-affinity routing measurably works: fleet-level radix hits in
  `clean` >= the `single` baseline, and strictly > `random`;
* every fault point armed in the chaos pass actually fired.

Deterministic end to end: workload, fault schedule, stepping order and
the shared engine/fleet clock all derive from --seed; wall-clock never
enters any engine. Bounded runtime: hard step ceiling.

Usage:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
            python tools/soak_fleet.py [--requests 120] [--seed 0]
(or `make soak-fleet`). Exits 0 on success, 1 with a report on
violation — a test harness like soak_serving.py, allowed to fail loud.

`--procs` (ISSUE 14, `make soak-fleet-proc`) runs the CROSS-PROCESS
chaos ladder instead: real worker processes behind the TCPStore
mailbox —

* in-process reference pass (also warms the shared compile cache) and
  the cold-vs-warm compile-cache bench (warm cold-start-to-first-token
  must be >= 5x faster than cold compile; a corrupted entry degrades
  to a counted recompile mid-bench);
* clean 3-worker cross-process pass — streams BIT-IDENTICAL to the
  in-process reference;
* chaos pass: seeded kill -9 of w0 mid-stream (worker.kill9, proven
  by the -SIGKILL returncode), a PERMANENTLY wedged w1
  (transport.stall times=-1 worker-side: no heartbeats out, no
  commands in -> the hard-stall ladder kills + adopts), w2 a
  slow-heartbeat worker under load (visible SUSPECT gaps, survives)
  that also absorbs a finite transport.stall (reported via heartbeat
  fired counts), with transport.drop / transport.duplicate armed
  host-side on the event streams. All requests complete bit-identical,
  zero lost, zero funnel conflicts, full reclamation on survivors;
* rolling restart: drain -> respawn -> adopt with exactly-once
  delivery, the successor warm-starting from the disk cache (zero
  recompiles), heartbeat gaps visible in the Prometheus text.

`--disagg` (ISSUE 18, `make soak-disagg`) runs the DISAGGREGATED
prefill/decode ladder: 2 prefill-role + 2 decode-role workers with
mid-flight KV handoff —

* clean pass: a 16-request prefill-heavy mixed load (shared-prefix
  hits included) streams BIT-IDENTICAL to the in-process co-located
  reference, with real KV pages shipped (handoffs_completed >= 1);
* decode-TPOT comparison: the same load on an all-"both" fleet of the
  SAME size; steady-state decode inter-token-gap p99 (per-token host
  stamps, first post-handoff gap excluded) must be LOWER on the
  disaggregated fleet — prefill chunks no longer interleave with
  decode steps;
* 3-seed chaos: kill -9 of a prefill worker MID-HANDOFF
  (fleet.handoff_partial: dies with only part of the kv_page stream
  shipped), kill -9 of a decode worker mid-decode (its adopted work
  re-lands on the surviving decode worker), host-armed
  fleet.handoff_stall (relay frames eaten -> phase timeout -> capped
  backoff -> re-pull), and a decode_reject refusal — every pass
  bit-identical, zero lost, zero funnel conflicts, full reclamation
  on survivors;
* role-starved fallback: a prefill-only fleet degrades every handoff
  to co-located execution (handoffs_colocated == streams) instead of
  shedding;
* int8-KV variant: the handoff ships quantized pages + scales,
  bit-identical to the int8 in-process reference.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# CPU pin BEFORE jax initializes (the hosting image's sitecustomize
# force-registers a TPU platform; mirror tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                                   # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np                                           # noqa: E402

import paddle_tpu as paddle                                  # noqa: E402
from paddle_tpu.models.llama import (LlamaConfig,            # noqa: E402
                                     LlamaForCausalLM)
from paddle_tpu.serving import (EngineOverloaded,            # noqa: E402
                                Fleet, PrefixAffinityRouter,
                                RandomRouter, RetryPolicy,
                                ServingEngine, TransientDeviceError)
from paddle_tpu.utils import faults                          # noqa: E402

# single-bucket grid: every pass hits identical program shapes, so the
# bit-identity comparison across clean/chaos is exact (SERVING.md
# determinism contract) — same discipline as soak_serving.py.
ENGINE_KW = dict(num_pages=40, page_size=8, token_budget=48,
                 batch_buckets=[8], prefill_buckets=[32], pages_buckets=[8],
                 temperature=0.0, max_queue_len=32)
STALL_TIMEOUT_S = 0.2   # ~200 clock ticks; detection within tens of steps
MAX_STEPS_FACTOR = 400  # hard ceiling: steps <= factor * num_requests
MAX_LIVE = 8            # client-side concurrency cap (see run_pass)
WARMUP = 2              # bare-prefix warmup requests (make_workload)


class FakeClock:
    """Shared engine+fleet clock: a fixed tick per observation, so
    heartbeat ages and deadlines are functions of call counts, never
    host wall-clock."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1e-3
        return self.t


def make_workload(n, seed):
    """Shared-prefix-heavy mix: two 2-page shared prefixes (the
    affinity router should pin each to one replica) + random fill.
    The first WARMUP requests carry each bare prefix — run_pass drains
    them before the main traffic so the hit-rate comparison measures
    ROUTING, not the admission race of a cold cache (two cold replicas
    can each admit a shared-prefix request before either donates, a
    concurrency artifact every router suffers equally)."""
    rng = np.random.RandomState(seed)
    prefix_a = rng.randint(0, 128, (16,)).tolist()
    prefix_b = rng.randint(0, 128, (16,)).tolist()
    work = [(list(prefix_a), 4), (list(prefix_b), 4)]
    for _ in range(n):
        u = rng.random()
        if u < 0.30:
            p = prefix_a + rng.randint(0, 128, (rng.randint(2, 8),)).tolist()
        elif u < 0.55:
            p = prefix_b + rng.randint(0, 128, (rng.randint(2, 8),)).tolist()
        else:
            p = rng.randint(0, 128, (rng.randint(4, 24),)).tolist()
        work.append((p, int(rng.randint(3, 10))))
    return work


def run_pass(model, work, *, n_replicas, router, chaos, seed, report,
             label, trace=None, keep=None):
    """One full soak pass; returns {workload idx: token stream}.
    `trace` (one RequestTracer SHARED by every replica — the migration
    contract) turns request tracing on; `keep` (a dict) receives the
    per-replica flight-recorder timelines and the fleet's Prometheus
    exposition before shutdown (ISSUE 10)."""
    clock = FakeClock()
    engines = [ServingEngine(
        model, clock=clock,
        retry_policy=RetryPolicy(max_retries=12, base_s=0.0,
                                 sleep=lambda s: None),
        trace=trace, **ENGINE_KW) for _ in range(n_replicas)]
    fleet = Fleet(engines, router=router, clock=clock,
                  stall_timeout_s=STALL_TIMEOUT_S)
    armed = set()

    def arm(name, **kwargs):
        faults.inject(name, **kwargs)
        armed.add(name)

    if chaos:
        # THE kill: replica-0 dies at its first step past the warmup
        # window — mid-stream, with requests in every state. times=-1 +
        # a name: other replicas consume firings and ignore them, the
        # victim cannot miss.
        arm("fleet.replica_crash", payload="replica-0", after=20,
            times=-1)
        # permanent stall of replica-1 a little later (hits accrue ~2
        # per fleet step once replica-0 is dead): the heartbeat stops,
        # the stall detector drains it around the wedge
        arm("fleet.stream_stall", payload="replica-1", after=60,
            times=-1)
        # routing races: the chosen replica "goes unhealthy between
        # scoring and submission"
        arm("fleet.route_race", payload=True, after=5, times=3)
        # engine-level noise underneath the fleet faults: transient
        # launch errors (retried in place; totals < max_retries by
        # construction) and allocator OOM (reclamation ladder)
        arm("serving.engine.prefill_chunk",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            after=3, times=1)
        arm("serving.engine.prefill_chunk",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            prob=0.02, times=9, seed=seed + 2)
        arm("serving.engine.decode_step",
            exc=TransientDeviceError("soak: relay loss"),
            after=4, times=1)
        arm("serving.engine.decode_step",
            exc=TransientDeviceError("soak: relay loss"),
            prob=0.02, times=9, seed=seed + 3)
        arm("serving.kv.alloc_page", payload=True, after=5, times=2)
        arm("serving.kv.alloc_page", payload=True,
            prob=0.03, times=12, seed=seed + 4)

    idx_of = {}
    handles = []
    pending = list(enumerate(work))
    sheds = 0
    steps = 0
    max_steps = MAX_STEPS_FACTOR * max(1, len(work))
    try:
        # warmup wave: the bare-prefix requests drain first (and donate
        # each prefix into exactly one replica's radix tree)
        for _ in range(WARMUP):
            i, (p, m) = pending.pop(0)
            h = fleet.submit(p, max_new_tokens=m)
            idx_of[h.request_id] = i
            handles.append(h)
        while fleet.has_work():
            fleet.step_all()
            steps += 1
        while pending or fleet.has_work():
            # fixed client-side concurrency (same offered load in every
            # pass, whatever the replica count): the routing criterion
            # compares hit rates, so the single-replica baseline and
            # the fleet must see the same admission dynamics — without
            # the cap the 3-replica fleet admits 3x faster and more
            # shared-prefix requests arrive before the first donation
            # (a cold-start artifact, not a routing property)
            admitted = 0
            while pending and admitted < 4 and \
                    sum(1 for h in handles if not h.finished) < MAX_LIVE:
                i, (p, m) = pending[0]
                try:
                    h = fleet.submit(p, max_new_tokens=m)
                except EngineOverloaded:
                    sheds += 1
                    break
                idx_of[h.request_id] = i
                handles.append(h)
                pending.pop(0)
                admitted += 1
            fleet.step_all()
            steps += 1
            if steps > max_steps:
                raise AssertionError(
                    f"[{label}] failed to drain after {steps} steps")

        out = {}
        reasons = {}
        for rid, i in idx_of.items():
            h = fleet.handle(rid)
            assert h.finished, f"[{label}] request {i} never finished"
            reasons[h.finish_reason] = reasons.get(h.finish_reason, 0) + 1
            out[i] = list(h.tokens)

        # ---- reclamation on EVERY pool (killed/stalled included) ----
        for r in fleet.replicas:
            if r.engine.radix is not None:
                r.engine.radix.check_invariants()
            r.engine.reset_prefix_cache()
            assert r.engine.allocator.num_used == 0, \
                f"[{label}] {r.name} leaked KV pages"
            r.engine.allocator.check_invariants()

        snap = fleet.merged_metrics().snapshot()
        report[label] = {
            "steps": steps, "sheds": sheds,
            "finish_reasons": reasons,
            "replica_states": {r.name: r.state.value
                               for r in fleet.replicas},
            "prefix_hits": snap["prefix_hits"],
            "cached_tokens_served": snap["cached_tokens_served"],
            "preemptions": snap["requests_preempted"],
            "step_retries": snap["step_retries"],
            "migrated": fleet.counters["requests_migrated"],
            "catchup_tokens": fleet.counters["catchup_tokens"],
            "lost": fleet.counters["requests_lost"],
            "deaths": fleet.counters["replica_deaths"],
            "stalls": fleet.counters["replica_stalls"],
            "route_races": fleet.counters["route_races"],
        }
        if chaos:
            fired = faults.fired_counts()
            report[f"fired_{label}"] = fired
            for pt in sorted(armed):
                assert fired.get(pt, 0) >= 1, \
                    f"[{label}] armed fault point {pt} never fired"
        if keep is not None:
            keep["timelines"] = [
                dict(rec, replica=r.name)
                for r in fleet.replicas for rec in r.engine.timeline()]
            keep["prometheus"] = fleet.prometheus_text()
            keep["migrated"] = fleet.counters["requests_migrated"]
        return out
    finally:
        faults.clear()
        faults.reset_counts()
        fleet.shutdown()


# ===================== cross-process ladder (ISSUE 14) =====================

CFG_DICT = dict(vocab_size=128, hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=2,
                num_key_value_heads=1, max_position_embeddings=128)
PROC_SUSPECT_S = 0.5
PROC_DEAD_S = 6.0


def _drive_engine(eng, work):
    """Drain `work` through one in-process engine with client-side
    pacing (the queue is bounded); returns {workload idx: stream}."""
    from paddle_tpu.serving import EngineOverloaded as _EO
    out, rid_of = {}, {}
    pending = list(enumerate(work))
    while pending or eng.has_work():
        while pending:
            i, (p, m) = pending[0]
            try:
                rid_of[eng.add_request(p, max_new_tokens=m)] = i
            except _EO:
                break
            pending.pop(0)
        for rid, tok in eng.step():
            out.setdefault(rid_of[rid], []).append(int(tok))
    return out


def _first_token_s(model, cache_dir):
    """Cold-start-to-first-token: fresh engine on `cache_dir`, one
    request, stepped to its first emission. Returns (seconds, the
    engine's CompileCache counters); the engine itself is drained and
    shut down here."""
    from paddle_tpu.serving import ServingEngine
    t0 = time.perf_counter()
    eng = ServingEngine(model, compile_cache=cache_dir, **ENGINE_KW)
    eng.add_request(list(range(1, 9)), max_new_tokens=2)
    emitted = []
    while not emitted:
        emitted = eng.step()
    dt = time.perf_counter() - t0
    eng.run()    # drain the tail so the engine ends clean
    cc = dict(eng.compile_cache.counters)
    eng.shutdown()
    eng.metrics.unregister()
    return dt, cc


def run_proc_pass(work, ref, ccdir, *, chaos, seed, report, label):
    """One cross-process pass over `work`; asserts bit-identity against
    the in-process reference streams and (chaos) the full fault
    ladder."""
    from paddle_tpu.serving import EngineOverloaded, ProcessFleet
    from paddle_tpu.serving.fleet.errors import NoHealthyReplica
    from paddle_tpu.serving.fleet.procfleet import WorkerState

    base = {"model": {"kind": "llama", "config": CFG_DICT, "seed": 0},
            "engine": ENGINE_KW, "heartbeat_interval_s": 0.05,
            "compile_cache_dir": ccdir}
    specs = {f"w{i}": dict(base) for i in range(3)}
    if chaos:
        # w0: seeded kill -9 mid-stream (proven by returncode -9)
        specs["w0"]["faults"] = [
            {"point": "worker.kill9", "after": 25, "times": 1}]
        # w1: permanently wedged transport — no heartbeats out, no
        # commands in; the hard-stall ladder must kill + adopt
        specs["w1"]["faults"] = [
            {"point": "transport.stall", "after": 40, "times": -1}]
        # w2: slow heartbeats under load (SUSPECT gaps, survives) + a
        # finite stall it recovers from and REPORTS (fired counts ride
        # its later heartbeats — the in-soak firing proof)
        specs["w2"]["heartbeat_interval_s"] = 1.0
        specs["w2"]["faults"] = [
            {"point": "transport.stall", "after": 60, "times": 3}]
    pf = ProcessFleet(specs, suspect_after_s=PROC_SUSPECT_S,
                      dead_after_s=PROC_DEAD_S,
                      max_inflight_per_worker=8,
                      stderr_dir=os.path.join("profiler_log",
                                              "soak_proc_workers"))
    armed_host = set()
    try:
        t0 = time.monotonic()
        while not all(w.ready for w in pf.workers.values()):
            pf.pump()
            if time.monotonic() - t0 > 120:
                raise AssertionError(f"[{label}] workers never ready")
            time.sleep(0.01)
        if chaos:
            # host-side wire damage on the worker->host streams: drops
            # heal through heartbeat snapshots, duplicates must die in
            # the exactly-once funnel
            faults.inject("transport.drop", payload=True, prob=0.02,
                          times=8, seed=seed + 11)
            faults.inject("transport.duplicate", payload=True,
                          prob=0.03, times=10, seed=seed + 12)
            armed_host |= {"transport.drop", "transport.duplicate"}

        idx_of = {}
        pending = list(enumerate(work))
        max_gap = {n: 0.0 for n in pf.workers}
        t0 = time.monotonic()
        while pending or pf.has_work():
            submitted = 0
            while pending and submitted < 4:
                i, (p, m) = pending[0]
                try:
                    h = pf.submit(p, max_new_tokens=m)
                except (EngineOverloaded, NoHealthyReplica):
                    break   # backpressure / mid-failover: retry later
                idx_of[h.request_id] = i
                pending.pop(0)
                submitted += 1
            pf.pump()
            for n in pf.workers:
                g = pf.heartbeat_gap_s(n)
                if g is not None and \
                        pf.workers[n].state not in (WorkerState.DEAD,
                                                    WorkerState.STOPPED):
                    max_gap[n] = max(max_gap[n], g)
            if time.monotonic() - t0 > 600:
                raise AssertionError(
                    f"[{label}] failed to drain after 600s; "
                    f"{pf.summary()}")
            time.sleep(2e-3)

        streams = {}
        for rid, i in idx_of.items():
            h = pf.handles[rid]
            assert h.finished, f"[{label}] request {i} never finished"
            assert h.finish_reason in ("stop", "length"), \
                f"[{label}] request {i} ended {h.finish_reason!r}"
            streams[i] = list(h.tokens)
        diverged = [i for i in streams if streams[i] != ref.get(i)]
        assert not diverged, \
            f"[{label}] cross-process streams diverged from the " \
            f"in-process reference: {diverged[:10]}"
        assert pf.counters["requests_lost"] == 0, pf.summary()
        assert pf.counters["funnel_conflicts"] == 0, pf.summary()

        # let the suspicion ladder RESOLVE every suspect (a wedged
        # worker must reach DEAD via the hard-stall timeout before the
        # reclamation sweep asks it anything)
        t0 = time.monotonic()
        while any(w.state is WorkerState.SUSPECT
                  for w in pf.workers.values()):
            pf.pump()
            if time.monotonic() - t0 > PROC_DEAD_S * 3:
                break
            time.sleep(0.01)

        # ---- full reclamation on every SURVIVING worker --------------
        for name, w in pf.workers.items():
            if w.state is not WorkerState.HEALTHY:
                continue
            st = pf.request_stats(name, reset_prefix_cache=True)
            assert st is not None, f"[{label}] no stats from {name}"
            assert st.get("radix_ok", True) and st["allocator_ok"], st
            assert st["kv_used_pages"] == 0, \
                f"[{label}] {name} leaked KV pages: {st}"

        report[label] = {
            "streams": len(streams),
            "max_heartbeat_gap_s": {n: round(g, 3)
                                    for n, g in max_gap.items()},
            "worker_states": {n: w.state.value
                              for n, w in pf.workers.items()},
            **{k: v for k, v in pf.counters.items() if v},
        }
        if chaos:
            host_fired = faults.fired_counts()
            worker_fired = pf.fired_counts()
            report[f"fired_{label}"] = {"host": host_fired,
                                        "worker": worker_fired}
            # every armed fault PROVEN fired:
            for pt in sorted(armed_host):
                assert host_fired.get(pt, 0) >= 1, \
                    f"[{label}] host-armed {pt} never fired"
            # kill9: the process really died by SIGKILL, mid-workload
            assert pf.workers["w0"].poll() == -9, \
                f"[{label}] w0 rc {pf.workers['w0'].poll()}"
            assert pf.counters["worker_kill9_observed"] >= 1
            # the wedged worker was hard-stalled out and its work moved
            assert pf.counters["worker_hard_stalls"] >= 1, pf.summary()
            assert pf.workers["w1"].state is WorkerState.DEAD
            assert pf.counters["requests_migrated"] >= 1, pf.summary()
            # w2 recovered from its finite stall and REPORTED it
            assert worker_fired.get("transport.stall", 0) >= 1, \
                f"[{label}] worker-side transport.stall unreported: " \
                f"{worker_fired}"
            # slow-heartbeat worker: visible gaps, still alive
            assert max_gap["w2"] > PROC_SUSPECT_S, max_gap
            assert pf.workers["w2"].state not in (WorkerState.DEAD,
                                                  WorkerState.STOPPED)
            # duplicates died in the funnel (asserted zero-conflict
            # above); count what the funnel absorbed
            report[label]["funnel_duplicates"] = \
                pf.counters["funnel_duplicates"]
        # heartbeat-gap visibility in the Prometheus text
        text = pf.prometheus_text()
        assert "worker_heartbeat_gap_seconds" in text
        report[f"prometheus_{label}_lines"] = text.count("\n")
        return streams, pf
    finally:
        faults.clear()
        faults.reset_counts()
        pf.shutdown()


def run_proc_ladder(args):
    """The --procs entry: reference + bench + clean + chaos + rolling
    restart. Returns the report dict (raises AssertionError on any
    violation)."""
    import shutil
    import tempfile

    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.fleet.procfleet import WorkerState

    report = {"requests": args.requests, "seed": args.seed,
              "mode": "procs"}
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(**CFG_DICT))
    work = make_workload(args.requests, args.seed)
    ccdir = tempfile.mkdtemp(prefix="soak_ptcc_")
    try:
        # ---- in-process reference (warms the shared cache) -----------
        ref_eng = ServingEngine(model, compile_cache=ccdir, **ENGINE_KW)
        try:
            ref = _drive_engine(ref_eng, work)
            saved = ref_eng.save_compile_cache()
        finally:
            ref_eng.shutdown()
        assert saved >= 2, f"compile cache saved only {saved} entries"
        report["cache_entries_saved"] = saved

        # ---- cold-vs-warm compile-cache bench ------------------------
        cold_dir = tempfile.mkdtemp(prefix="soak_ptcc_cold_")
        try:
            t_cold, _ = _first_token_s(model, cold_dir)
        finally:
            shutil.rmtree(cold_dir, ignore_errors=True)
        # a corrupted entry must degrade to a counted recompile,
        # mid-bench, without crashing the engine
        faults.inject("cache.corrupt_entry", payload=True, times=1)
        t_warm, warm_cc = _first_token_s(model, ccdir)
        corrupt_fired = faults.fired_counts().get("cache.corrupt_entry",
                                                  0)
        faults.clear()
        faults.reset_counts()
        assert corrupt_fired >= 1, "cache.corrupt_entry never fired"
        assert warm_cc["rejects"] >= 1
        # second warm engine, undamaged: the actual warm number
        t_warm2, _ = _first_token_s(model, ccdir)
        t_warm = min(t_warm, t_warm2)
        speedup = t_cold / t_warm
        report["compile_cache_bench"] = {
            "cold_first_token_s": round(t_cold, 3),
            "warm_first_token_s": round(t_warm, 3),
            "speedup": round(speedup, 2),
            "corrupt_entry_rejects": warm_cc["rejects"]}
        assert speedup >= 5.0, \
            f"warm cold-start-to-first-token only {speedup:.1f}x " \
            f"faster than cold compile (need >= 5x)"

        # ---- clean + chaos cross-process passes ----------------------
        run_proc_pass(work, ref, ccdir, chaos=False, seed=args.seed,
                      report=report, label="proc_clean")
        run_proc_pass(work, ref, ccdir, chaos=True, seed=args.seed,
                      report=report, label="proc_chaos")

        # ---- rolling restart: drain -> respawn -> adopt --------------
        from paddle_tpu.serving import ProcessFleet
        base = {"model": {"kind": "llama", "config": CFG_DICT,
                          "seed": 0},
                "engine": ENGINE_KW, "heartbeat_interval_s": 0.05,
                "compile_cache_dir": ccdir}
        pf = ProcessFleet({"w0": dict(base), "w1": dict(base)},
                          suspect_after_s=PROC_SUSPECT_S,
                          dead_after_s=30.0,
                          stderr_dir=os.path.join(
                              "profiler_log", "soak_proc_workers"))
        try:
            t0 = time.monotonic()
            while not all(w.ready for w in pf.workers.values()):
                pf.pump()
                assert time.monotonic() - t0 < 120
                time.sleep(0.01)
            long_work = [(p, 24) for p, _ in work[:8]]
            handles = []
            for p, m in long_work:
                handles.append(pf.submit(p, max_new_tokens=m))
            # first tokens, then restart w0 under load
            t0 = time.monotonic()
            while not all(h.tokens for h in handles):
                pf.pump()
                assert time.monotonic() - t0 < 120
                time.sleep(5e-3)
            pf.rolling_restart("w0")
            res = pf.run(timeout_s=300)
            # per-request streams are batch-invariant (the SERVING.md
            # determinism contract), so ONE warm reference engine
            # serves all 8 expected streams
            solo = ServingEngine(model, compile_cache=ccdir,
                                 **ENGINE_KW)
            try:
                rids = [solo.add_request(p, max_new_tokens=m)
                        for p, m in long_work]
                solo_out = solo.run()
            finally:
                solo.shutdown()
            for i, h in enumerate(handles):
                assert res[h.request_id] == solo_out[rids[i]], \
                    f"rolling restart diverged request {i}"
            assert pf.counters["requests_lost"] == 0
            assert pf.counters["funnel_conflicts"] == 0
            assert pf.counters["worker_drains"] == 1
            assert pf.counters["worker_restarts"] == 1
            # successor warm-starts from disk: route it fresh traffic
            # (the migrated work may have landed on the other worker),
            # then its heartbeat counters must show disk hits and ZERO
            # XLA compiles — the no-compile-storm restart criterion
            t0 = time.monotonic()
            while not pf.workers["w0"].ready:
                pf.pump()
                assert time.monotonic() - t0 < 120, \
                    "respawned successor never became ready"
                time.sleep(0.01)
            for p, _ in work[8:12]:
                pf.submit(p, max_new_tokens=6)
            pf.run(timeout_s=120)
            t0 = time.monotonic()
            while (pf.workers["w0"].last_beat is None or
                   pf.workers["w0"].last_beat["counters"]
                   ["engine_steps"] == 0):
                pf.pump()
                assert time.monotonic() - t0 < 60, \
                    "successor never stepped"
                time.sleep(5e-3)
            wc = pf.workers["w0"].last_beat["counters"]
            assert wc["recompiles"] == 0, wc
            assert wc["compile_cache_hits"] >= 1, wc
            assert pf.counters["requests_lost"] == 0
            text = pf.prometheus_text()
            assert 'worker_heartbeat_gap_seconds{worker="w0"}' in text
            assert 'paddle_serving_worker_generation{worker="w0"} 1' \
                in text
            report["rolling_restart"] = {
                "streams": len(handles),
                "migrated": pf.counters["requests_migrated"],
                "successor_cache_hits": wc["compile_cache_hits"],
            }
        finally:
            pf.shutdown()
        return report
    finally:
        shutil.rmtree(ccdir, ignore_errors=True)


# ============== disaggregated prefill/decode ladder (ISSUE 18) =============

def make_disagg_workload(n, seed):
    """Prefill-heavy mixed load: long prompts (2-4 pages, so every
    handoff has real KV to ship) with the two shared prefixes still in
    the mix — the bit-identity pass exercises prefix-cache hits ACROSS
    the handoff, not just cold pulls."""
    rng = np.random.RandomState(seed + 1000)
    prefix_a = rng.randint(0, 128, (16,)).tolist()
    prefix_b = rng.randint(0, 128, (16,)).tolist()
    work = [(list(prefix_a), 4), (list(prefix_b), 4)]
    for _ in range(n - 2):
        u = rng.random()
        if u < 0.25:
            p = prefix_a + rng.randint(0, 128,
                                       (rng.randint(4, 12),)).tolist()
        elif u < 0.50:
            p = prefix_b + rng.randint(0, 128,
                                       (rng.randint(4, 12),)).tolist()
        else:
            p = rng.randint(0, 128, (rng.randint(16, 28),)).tolist()
        work.append((p, int(rng.randint(6, 12))))
    return work


def _decode_tpot_gaps(handles):
    """Steady-state decode inter-token gaps (seconds) from the per-
    token host stamps. The FIRST gap is excluded on purpose: in the
    disaggregated fleet it contains the handoff itself (pull + adopt),
    in the co-located fleet the post-prefill scheduling seam — TPOT is
    the steady decode cadence, not the transition. Catch-up bursts
    (many tokens on one stamp) only happen in chaos passes, so callers
    measure CLEAN passes only."""
    gaps = []
    for h in handles:
        ts = h.token_ts
        gaps.extend(b - a for a, b in zip(ts[1:], ts[2:]))
    return gaps


def run_disagg_pass(work, ref, ccdir, *, label, report, roles,
                    engine_kw=None, worker_faults=None, host_faults=None,
                    expect=None):
    """One cross-process pass with role-tagged workers; asserts
    bit-identity against `ref`, zero loss, zero funnel conflicts and
    full reclamation on every surviving worker. `roles` maps worker
    name -> role; `worker_faults` maps worker name -> spec fault list;
    `host_faults` arms supervisor-side points once workers are ready;
    `expect(pf)` runs scenario-specific assertions before shutdown.
    Returns the decode-TPOT gap samples."""
    from paddle_tpu.serving import EngineOverloaded, ProcessFleet
    from paddle_tpu.serving.fleet.errors import NoHealthyReplica
    from paddle_tpu.serving.fleet.procfleet import WorkerState

    kw = dict(engine_kw or ENGINE_KW)
    specs = {}
    for name, role in roles.items():
        specs[name] = {"model": {"kind": "llama", "config": CFG_DICT,
                                 "seed": 0},
                       "engine": kw, "heartbeat_interval_s": 0.05,
                       "compile_cache_dir": ccdir, "role": role}
        if worker_faults and name in worker_faults:
            specs[name]["faults"] = worker_faults[name]
    pf = ProcessFleet(specs, suspect_after_s=PROC_SUSPECT_S,
                      dead_after_s=PROC_DEAD_S,
                      handoff_timeout_s=1.0, handoff_backoff_s=0.1,
                      max_inflight_per_worker=8,
                      stderr_dir=os.path.join("profiler_log",
                                              "soak_disagg_workers"))
    try:
        t0 = time.monotonic()
        while not all(w.ready for w in pf.workers.values()):
            pf.pump()
            if time.monotonic() - t0 > 120:
                raise AssertionError(f"[{label}] workers never ready")
            time.sleep(0.01)
        for name, kws in (host_faults or {}).items():
            faults.inject(name, **kws)

        idx_of = {}
        pending = list(enumerate(work))
        t0 = time.monotonic()
        while pending or pf.has_work():
            submitted = 0
            while pending and submitted < 4:
                i, (p, m) = pending[0]
                try:
                    h = pf.submit(p, max_new_tokens=m)
                except (EngineOverloaded, NoHealthyReplica):
                    break
                idx_of[h.request_id] = i
                pending.pop(0)
                submitted += 1
            pf.pump()
            if time.monotonic() - t0 > 600:
                raise AssertionError(
                    f"[{label}] failed to drain after 600s; "
                    f"{pf.summary()}")
            time.sleep(2e-3)

        handles = [pf.handles[rid] for rid in idx_of]
        streams = {}
        for rid, i in idx_of.items():
            h = pf.handles[rid]
            assert h.finished, f"[{label}] request {i} never finished"
            streams[i] = list(h.tokens)
        diverged = [i for i in streams if streams[i] != ref.get(i)]
        assert not diverged, \
            f"[{label}] disaggregated streams diverged from the " \
            f"co-located reference: {diverged[:10]}"
        assert pf.counters["requests_lost"] == 0, pf.summary()
        assert pf.counters["funnel_conflicts"] == 0, pf.summary()

        # every handoff entry resolved — nothing mid-flight at drain
        assert not pf._handoffs, pf.summary()
        # let the suspicion ladder resolve before the reclamation sweep
        t0 = time.monotonic()
        while any(w.state is WorkerState.SUSPECT
                  for w in pf.workers.values()):
            pf.pump()
            if time.monotonic() - t0 > PROC_DEAD_S * 3:
                break
            time.sleep(0.01)
        for name, w in pf.workers.items():
            if w.state is not WorkerState.HEALTHY:
                continue
            st = pf.request_stats(name, reset_prefix_cache=True)
            assert st is not None, f"[{label}] no stats from {name}"
            assert st.get("radix_ok", True) and st["allocator_ok"], st
            assert st["kv_used_pages"] == 0, \
                f"[{label}] {name} leaked KV pages: {st}"

        if expect is not None:
            expect(pf)
        report[label] = {
            "streams": len(streams),
            "worker_states": {n: w.state.value
                              for n, w in pf.workers.items()},
            **{k: v for k, v in pf.counters.items() if v},
        }
        return _decode_tpot_gaps(handles)
    finally:
        faults.clear()
        faults.reset_counts()
        pf.shutdown()


def run_disagg_ladder(args):
    """The --disagg entry: co-located reference + TPOT strawman, clean
    disaggregated pass, 3-seed chaos, role-starved fallback, int8-KV
    variant. Returns the report dict (AssertionError on violation)."""
    import shutil
    import tempfile

    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.fleet.procfleet import WorkerState

    report = {"requests": args.requests, "seed": args.seed,
              "mode": "disagg"}
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(**CFG_DICT))
    n = max(16, args.requests // 4)   # per-pass size; chaos runs 3 seeds
    ccdir = tempfile.mkdtemp(prefix="soak_dgcc_")
    try:
        def reference(work, **ekw):
            eng = ServingEngine(model, compile_cache=ccdir,
                                **dict(ENGINE_KW, **ekw))
            try:
                out = _drive_engine(eng, work)
                eng.save_compile_cache()
            finally:
                eng.shutdown()
            return out

        work = make_disagg_workload(n, args.seed)
        ref = reference(work)

        # ---- co-located strawman (same worker count, all "both"):
        # the chunked-prefill interference baseline the decode-TPOT
        # criterion is measured against
        coloc_roles = {f"w{i}": "both" for i in range(4)}
        coloc_gaps = run_disagg_pass(
            work, ref, ccdir, label="coloc", report=report,
            roles=coloc_roles)

        # ---- clean disaggregated pass: 2 prefill + 2 decode ----------
        roles = {"p0": "prefill", "p1": "prefill",
                 "d0": "decode", "d1": "decode"}

        def expect_clean(pf):
            assert pf.counters["handoffs_started"] >= len(work) - 2, \
                pf.summary()
            assert pf.counters["handoffs_completed"] >= 1, pf.summary()
            assert pf.counters["kv_pages_shipped"] >= 2, pf.summary()
            assert pf.counters["handoffs_colocated"] == 0, pf.summary()
            text = pf.prometheus_text()
            assert 'role="prefill"' in text and 'role="decode"' in text
            assert "fleet_kv_pages_shipped" in text

        disagg_gaps = run_disagg_pass(
            work, ref, ccdir, label="disagg_clean", report=report,
            roles=roles, expect=expect_clean)

        # ---- decode-TPOT criterion -----------------------------------
        p99 = lambda g: float(np.percentile(np.asarray(g), 99))  # noqa: E731
        tpot = {"coloc_p99_ms": round(p99(coloc_gaps) * 1e3, 3),
                "disagg_p99_ms": round(p99(disagg_gaps) * 1e3, 3),
                "coloc_samples": len(coloc_gaps),
                "disagg_samples": len(disagg_gaps)}
        tpot["ratio"] = round(tpot["coloc_p99_ms"]
                              / max(tpot["disagg_p99_ms"], 1e-9), 2)
        report["decode_tpot"] = tpot
        assert tpot["disagg_p99_ms"] < tpot["coloc_p99_ms"], \
            f"decode TPOT p99 not improved by disaggregation: {tpot}"

        # ---- 3-seed chaos ladder -------------------------------------
        for k in range(3):
            seed = args.seed + k
            cwork = make_disagg_workload(n, seed)
            cref = reference(cwork)

            def expect_chaos(pf):
                # the prefill worker really died -9 MID-HANDOFF...
                assert pf.workers["p0"].poll() == -9, \
                    pf.workers["p0"].poll()
                assert pf.workers["p0"].state is WorkerState.DEAD
                # ... and the decode worker mid-decode
                assert pf.workers["d0"].poll() == -9, \
                    pf.workers["d0"].poll()
                # interrupted handoffs degraded instead of wedging:
                # re-prefilled (refetched / migrated) or re-pulled
                assert (pf.counters["handoffs_refetched"]
                        + pf.counters["requests_migrated"]) >= 1, \
                    pf.summary()
                # the host-armed stall fired and the state machine
                # noticed (phase deadline -> backoff -> re-pull)
                assert faults.fired_counts().get(
                    "fleet.handoff_stall", 0) >= 1
                assert pf.counters["handoff_stalls"] >= 1, pf.summary()

            run_disagg_pass(
                cwork, cref, ccdir, label=f"disagg_chaos_s{seed}",
                report=report, roles=roles,
                worker_faults={
                    # p0: SIGKILL itself with only part of the kv_page
                    # stream shipped (the mid-flight death)
                    "p0": [{"point": "fleet.handoff_partial",
                            "after": k, "times": 1}],
                    # d0: die mid-decode a little into the run, adopted
                    # work re-lands on d1
                    "d0": [{"point": "worker.kill9",
                            "after": 80 + 40 * k, "times": 1}],
                    # d1: refuse its first adopt batch (typed reject ->
                    # supervisor re-routes)
                    "d1": [{"point": "fleet.decode_reject",
                            "after": k, "times": 1}],
                },
                host_faults={
                    # eat kv_page frames at the supervisor relay: the
                    # phase deadline must fire and the pull re-issue.
                    # after= skips the EARLY relays — those pulls tend
                    # to resolve through the p0/d0 death branches
                    # (donor-evacuation / target-reroute), which would
                    # mask the deadline path this scenario is proving
                    "fleet.handoff_stall": dict(payload=True,
                                                after=6 + 2 * k,
                                                times=2),
                },
                expect=expect_chaos)

        # ---- role-starved fallback: prefill-only fleet ---------------
        def expect_starved(pf):
            assert pf.counters["handoffs_colocated"] >= len(work) - 2, \
                pf.summary()
            assert pf.counters["handoffs_completed"] == 0, pf.summary()

        run_disagg_pass(
            work, ref, ccdir, label="role_starved", report=report,
            roles={"p0": "prefill", "p1": "prefill"},
            expect=expect_starved)

        # ---- int8-KV variant: quantized pages + scales ship ----------
        i8work = make_disagg_workload(8, args.seed + 7)
        i8ref = reference(i8work, kv_dtype="int8")

        def expect_int8(pf):
            assert pf.counters["handoffs_completed"] >= 1, pf.summary()
            assert pf.counters["kv_pages_shipped"] >= 2, pf.summary()

        run_disagg_pass(
            i8work, i8ref, ccdir, label="disagg_int8", report=report,
            roles={"p0": "prefill", "d0": "decode"},
            engine_kw=dict(ENGINE_KW, kv_dtype="int8"),
            expect=expect_int8)
        return report
    finally:
        shutil.rmtree(ccdir, ignore_errors=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--procs", action="store_true",
                    help="run the cross-process chaos ladder "
                         "(ISSUE 14) instead of the in-process soak")
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregated prefill/decode ladder "
                         "(ISSUE 18): role-split fleet, mid-flight KV "
                         "handoff chaos, decode-TPOT comparison")
    ap.add_argument("--trace-out",
                    default=os.path.join("profiler_log",
                                         "soak_fleet_trace.json"),
                    help="where the traced chaos pass exports the "
                         "MERGED chrome-trace JSON (profiler host "
                         "spans + request lifecycles, ISSUE 10)")
    args = ap.parse_args(argv)

    if args.procs:
        t0 = time.perf_counter()
        report = run_proc_ladder(args)
        report["wall_s"] = round(time.perf_counter() - t0, 2)
        print(json.dumps(report))
        print("SOAK_FLEET_PROC_OK")
        return 0

    if args.disagg:
        t0 = time.perf_counter()
        report = run_disagg_ladder(args)
        report["wall_s"] = round(time.perf_counter() - t0, 2)
        print(json.dumps(report))
        print("SOAK_FLEET_DISAGG_OK")
        return 0

    cfg = LlamaConfig(**CFG_DICT)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    work = make_workload(args.requests, args.seed)

    report = {"requests": args.requests, "seed": args.seed}
    t0 = time.perf_counter()
    single = run_pass(model, work, n_replicas=1,
                      router=PrefixAffinityRouter(), chaos=False,
                      seed=args.seed, report=report, label="single")
    clean = run_pass(model, work, n_replicas=3,
                     router=PrefixAffinityRouter(), chaos=False,
                     seed=args.seed, report=report, label="clean")
    chaos = run_pass(model, work, n_replicas=3,
                     router=PrefixAffinityRouter(), chaos=True,
                     seed=args.seed, report=report, label="chaos")
    rand = run_pass(model, work, n_replicas=3,
                    router=RandomRouter(seed=args.seed + 7), chaos=False,
                    seed=args.seed, report=report, label="random")

    # ---- traced chaos pass (ISSUE 10): the SAME kill/stall chaos with
    # one fleet-shared RequestTracer + an active Profiler, exported as
    # ONE merged chrome-trace JSON — profiler host spans and request
    # lifecycle rows on the shared perf_counter clock (the acceptance
    # artifact); migration park/adopt marks come from the kill.
    from paddle_tpu import profiler
    from paddle_tpu.serving import RequestTracer
    tracer = RequestTracer(max_completed=4 * max(1, args.requests))
    keep = {}
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                             on_trace_ready=lambda p: None)
    prof.start()
    try:
        traced = run_pass(model, work, n_replicas=3,
                          router=PrefixAffinityRouter(), chaos=True,
                          seed=args.seed, report=report, label="traced",
                          trace=tracer, keep=keep)
    finally:
        prof.stop()
    tdiv = [i for i in range(len(work))
            if traced.get(i) != clean.get(i)]
    assert not tdiv, f"tracing perturbed chaos streams: {tdiv[:10]}"
    migrated_traces = [t for t in tracer.traces()
                       if "park" in t.mark_names()
                       and "adopt" in t.mark_names()]
    assert keep["migrated"] == 0 or migrated_traces, \
        "migrations happened but no trace carries park+adopt marks"
    os.makedirs(os.path.dirname(args.trace_out) or ".", exist_ok=True)
    doc = tracer.export(args.trace_out, include_profiler=True,
                        flight_recorder=keep["timelines"])
    cats = {e.get("cat") for e in doc["traceEvents"]}
    assert "request" in cats and len(cats - {"request", None}) >= 1, \
        f"merged export missing host or request spans: {cats}"
    report["trace_out"] = args.trace_out
    report["traced_migration_traces"] = len(migrated_traces)

    # ---- zero-loss failover: EVERY request bit-identical -------------
    diverged = [i for i in range(len(work)) if chaos.get(i) != clean.get(i)]
    assert not diverged, \
        f"chaos streams diverged from the clean run: {diverged[:10]}"
    assert report["chaos"]["lost"] == 0, report["chaos"]
    assert report["chaos"]["deaths"] == 1, report["chaos"]
    assert report["chaos"]["stalls"] == 1, report["chaos"]
    assert report["chaos"]["migrated"] >= 1, report["chaos"]
    report["bit_identical_requests"] = len(work)

    # single-replica sanity: affinity fleet = single replica tokens too
    # (the routing layer must never change WHAT is generated)
    div1 = [i for i in range(len(work)) if single.get(i) != clean.get(i)]
    assert not div1, f"fleet changed tokens vs single replica: {div1[:10]}"

    # ---- the routing criterion ---------------------------------------
    hits_single = report["single"]["prefix_hits"]
    hits_aff = report["clean"]["prefix_hits"]
    hits_rand = report["random"]["prefix_hits"]
    assert hits_single > 0, report["single"]
    assert hits_aff >= hits_single, \
        f"affinity fleet hit rate fell below the single-replica " \
        f"baseline: {hits_aff} < {hits_single}"
    assert hits_aff > hits_rand, \
        f"affinity routing did not beat random spray: " \
        f"{hits_aff} <= {hits_rand}"

    report["wall_s"] = round(time.perf_counter() - t0, 2)
    print(json.dumps(report))
    # ---- final report through the observability paths (ISSUE 10) -----
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    print(trace_report.report(trace_report.load(args.trace_out)))
    print("== fleet metrics exposition (traced chaos pass) ==")
    print(keep.get("prometheus", ""), end="")
    print("SOAK_FLEET_OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"SOAK_FLEET_FAILED: {e}", file=sys.stderr)
        sys.exit(1)
