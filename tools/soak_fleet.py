"""Multi-replica chaos soak for the fleet front-end (ISSUE 7).

Runs the SAME seeded shared-prefix-heavy workload four times on CPU:

* `single`  — one replica, prefix-affinity router, no faults: the
  PR-2-style single-replica radix baseline the routing criterion is
  measured against;
* `clean`   — three replicas, prefix-affinity router, no faults: the
  reference token streams;
* `chaos`   — three replicas, prefix-affinity router, with a seeded
  KILL of replica-0 mid-stream (`fleet.replica_crash`), a permanent
  STALL of replica-1 (`fleet.stream_stall` -> stall detector), routing
  races, injected allocator OOM, and transient step errors;
* `random`  — three replicas, seeded RandomRouter, no faults: the
  routing-criterion strawman.

Acceptance assertions (ISSUE 7):

* zero-loss failover: EVERY accepted request completes in the chaos
  pass, with its token stream BIT-IDENTICAL to the clean pass (zero
  lost requests, zero duplicated or reordered tokens — migration
  preserves tokens-so-far and greedy continuation is deterministic
  under the pinned bucket grid);
* full page/refcount reclamation on every replica's pool — including
  the killed and the stalled one (vacate at evacuation);
* prefix-affinity routing measurably works: fleet-level radix hits in
  `clean` >= the `single` baseline, and strictly > `random`;
* every fault point armed in the chaos pass actually fired.

Deterministic end to end: workload, fault schedule, stepping order and
the shared engine/fleet clock all derive from --seed; wall-clock never
enters any engine. Bounded runtime: hard step ceiling.

Usage:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
            python tools/soak_fleet.py [--requests 120] [--seed 0]
(or `make soak-fleet`). Exits 0 on success, 1 with a report on
violation — a test harness like soak_serving.py, allowed to fail loud.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# CPU pin BEFORE jax initializes (the hosting image's sitecustomize
# force-registers a TPU platform; mirror tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                                   # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np                                           # noqa: E402

import paddle_tpu as paddle                                  # noqa: E402
from paddle_tpu.models.llama import (LlamaConfig,            # noqa: E402
                                     LlamaForCausalLM)
from paddle_tpu.serving import (EngineOverloaded,            # noqa: E402
                                Fleet, PrefixAffinityRouter,
                                RandomRouter, RetryPolicy,
                                ServingEngine, TransientDeviceError)
from paddle_tpu.utils import faults                          # noqa: E402

# single-bucket grid: every pass hits identical program shapes, so the
# bit-identity comparison across clean/chaos is exact (SERVING.md
# determinism contract) — same discipline as soak_serving.py.
ENGINE_KW = dict(num_pages=40, page_size=8, token_budget=48,
                 batch_buckets=[8], prefill_buckets=[32], pages_buckets=[8],
                 temperature=0.0, max_queue_len=32)
STALL_TIMEOUT_S = 0.2   # ~200 clock ticks; detection within tens of steps
MAX_STEPS_FACTOR = 400  # hard ceiling: steps <= factor * num_requests
MAX_LIVE = 8            # client-side concurrency cap (see run_pass)
WARMUP = 2              # bare-prefix warmup requests (make_workload)


class FakeClock:
    """Shared engine+fleet clock: a fixed tick per observation, so
    heartbeat ages and deadlines are functions of call counts, never
    host wall-clock."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1e-3
        return self.t


def make_workload(n, seed):
    """Shared-prefix-heavy mix: two 2-page shared prefixes (the
    affinity router should pin each to one replica) + random fill.
    The first WARMUP requests carry each bare prefix — run_pass drains
    them before the main traffic so the hit-rate comparison measures
    ROUTING, not the admission race of a cold cache (two cold replicas
    can each admit a shared-prefix request before either donates, a
    concurrency artifact every router suffers equally)."""
    rng = np.random.RandomState(seed)
    prefix_a = rng.randint(0, 128, (16,)).tolist()
    prefix_b = rng.randint(0, 128, (16,)).tolist()
    work = [(list(prefix_a), 4), (list(prefix_b), 4)]
    for _ in range(n):
        u = rng.random()
        if u < 0.30:
            p = prefix_a + rng.randint(0, 128, (rng.randint(2, 8),)).tolist()
        elif u < 0.55:
            p = prefix_b + rng.randint(0, 128, (rng.randint(2, 8),)).tolist()
        else:
            p = rng.randint(0, 128, (rng.randint(4, 24),)).tolist()
        work.append((p, int(rng.randint(3, 10))))
    return work


def run_pass(model, work, *, n_replicas, router, chaos, seed, report,
             label, trace=None, keep=None):
    """One full soak pass; returns {workload idx: token stream}.
    `trace` (one RequestTracer SHARED by every replica — the migration
    contract) turns request tracing on; `keep` (a dict) receives the
    per-replica flight-recorder timelines and the fleet's Prometheus
    exposition before shutdown (ISSUE 10)."""
    clock = FakeClock()
    engines = [ServingEngine(
        model, clock=clock,
        retry_policy=RetryPolicy(max_retries=12, base_s=0.0,
                                 sleep=lambda s: None),
        trace=trace, **ENGINE_KW) for _ in range(n_replicas)]
    fleet = Fleet(engines, router=router, clock=clock,
                  stall_timeout_s=STALL_TIMEOUT_S)
    armed = set()

    def arm(name, **kwargs):
        faults.inject(name, **kwargs)
        armed.add(name)

    if chaos:
        # THE kill: replica-0 dies at its first step past the warmup
        # window — mid-stream, with requests in every state. times=-1 +
        # a name: other replicas consume firings and ignore them, the
        # victim cannot miss.
        arm("fleet.replica_crash", payload="replica-0", after=20,
            times=-1)
        # permanent stall of replica-1 a little later (hits accrue ~2
        # per fleet step once replica-0 is dead): the heartbeat stops,
        # the stall detector drains it around the wedge
        arm("fleet.stream_stall", payload="replica-1", after=60,
            times=-1)
        # routing races: the chosen replica "goes unhealthy between
        # scoring and submission"
        arm("fleet.route_race", payload=True, after=5, times=3)
        # engine-level noise underneath the fleet faults: transient
        # launch errors (retried in place; totals < max_retries by
        # construction) and allocator OOM (reclamation ladder)
        arm("serving.engine.prefill_chunk",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            after=3, times=1)
        arm("serving.engine.prefill_chunk",
            exc=TransientDeviceError("soak: UNAVAILABLE"),
            prob=0.02, times=9, seed=seed + 2)
        arm("serving.engine.decode_step",
            exc=TransientDeviceError("soak: relay loss"),
            after=4, times=1)
        arm("serving.engine.decode_step",
            exc=TransientDeviceError("soak: relay loss"),
            prob=0.02, times=9, seed=seed + 3)
        arm("serving.kv.alloc_page", payload=True, after=5, times=2)
        arm("serving.kv.alloc_page", payload=True,
            prob=0.03, times=12, seed=seed + 4)

    idx_of = {}
    handles = []
    pending = list(enumerate(work))
    sheds = 0
    steps = 0
    max_steps = MAX_STEPS_FACTOR * max(1, len(work))
    try:
        # warmup wave: the bare-prefix requests drain first (and donate
        # each prefix into exactly one replica's radix tree)
        for _ in range(WARMUP):
            i, (p, m) = pending.pop(0)
            h = fleet.submit(p, max_new_tokens=m)
            idx_of[h.request_id] = i
            handles.append(h)
        while fleet.has_work():
            fleet.step_all()
            steps += 1
        while pending or fleet.has_work():
            # fixed client-side concurrency (same offered load in every
            # pass, whatever the replica count): the routing criterion
            # compares hit rates, so the single-replica baseline and
            # the fleet must see the same admission dynamics — without
            # the cap the 3-replica fleet admits 3x faster and more
            # shared-prefix requests arrive before the first donation
            # (a cold-start artifact, not a routing property)
            admitted = 0
            while pending and admitted < 4 and \
                    sum(1 for h in handles if not h.finished) < MAX_LIVE:
                i, (p, m) = pending[0]
                try:
                    h = fleet.submit(p, max_new_tokens=m)
                except EngineOverloaded:
                    sheds += 1
                    break
                idx_of[h.request_id] = i
                handles.append(h)
                pending.pop(0)
                admitted += 1
            fleet.step_all()
            steps += 1
            if steps > max_steps:
                raise AssertionError(
                    f"[{label}] failed to drain after {steps} steps")

        out = {}
        reasons = {}
        for rid, i in idx_of.items():
            h = fleet.handle(rid)
            assert h.finished, f"[{label}] request {i} never finished"
            reasons[h.finish_reason] = reasons.get(h.finish_reason, 0) + 1
            out[i] = list(h.tokens)

        # ---- reclamation on EVERY pool (killed/stalled included) ----
        for r in fleet.replicas:
            if r.engine.radix is not None:
                r.engine.radix.check_invariants()
            r.engine.reset_prefix_cache()
            assert r.engine.allocator.num_used == 0, \
                f"[{label}] {r.name} leaked KV pages"
            r.engine.allocator.check_invariants()

        snap = fleet.merged_metrics().snapshot()
        report[label] = {
            "steps": steps, "sheds": sheds,
            "finish_reasons": reasons,
            "replica_states": {r.name: r.state.value
                               for r in fleet.replicas},
            "prefix_hits": snap["prefix_hits"],
            "cached_tokens_served": snap["cached_tokens_served"],
            "preemptions": snap["requests_preempted"],
            "step_retries": snap["step_retries"],
            "migrated": fleet.counters["requests_migrated"],
            "catchup_tokens": fleet.counters["catchup_tokens"],
            "lost": fleet.counters["requests_lost"],
            "deaths": fleet.counters["replica_deaths"],
            "stalls": fleet.counters["replica_stalls"],
            "route_races": fleet.counters["route_races"],
        }
        if chaos:
            fired = faults.fired_counts()
            report[f"fired_{label}"] = fired
            for pt in sorted(armed):
                assert fired.get(pt, 0) >= 1, \
                    f"[{label}] armed fault point {pt} never fired"
        if keep is not None:
            keep["timelines"] = [
                dict(rec, replica=r.name)
                for r in fleet.replicas for rec in r.engine.timeline()]
            keep["prometheus"] = fleet.prometheus_text()
            keep["migrated"] = fleet.counters["requests_migrated"]
        return out
    finally:
        faults.clear()
        faults.reset_counts()
        fleet.shutdown()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace-out",
                    default=os.path.join("profiler_log",
                                         "soak_fleet_trace.json"),
                    help="where the traced chaos pass exports the "
                         "MERGED chrome-trace JSON (profiler host "
                         "spans + request lifecycles, ISSUE 10)")
    args = ap.parse_args(argv)

    cfg = LlamaConfig(vocab_size=128, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=1,
                      max_position_embeddings=128)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    work = make_workload(args.requests, args.seed)

    report = {"requests": args.requests, "seed": args.seed}
    t0 = time.perf_counter()
    single = run_pass(model, work, n_replicas=1,
                      router=PrefixAffinityRouter(), chaos=False,
                      seed=args.seed, report=report, label="single")
    clean = run_pass(model, work, n_replicas=3,
                     router=PrefixAffinityRouter(), chaos=False,
                     seed=args.seed, report=report, label="clean")
    chaos = run_pass(model, work, n_replicas=3,
                     router=PrefixAffinityRouter(), chaos=True,
                     seed=args.seed, report=report, label="chaos")
    rand = run_pass(model, work, n_replicas=3,
                    router=RandomRouter(seed=args.seed + 7), chaos=False,
                    seed=args.seed, report=report, label="random")

    # ---- traced chaos pass (ISSUE 10): the SAME kill/stall chaos with
    # one fleet-shared RequestTracer + an active Profiler, exported as
    # ONE merged chrome-trace JSON — profiler host spans and request
    # lifecycle rows on the shared perf_counter clock (the acceptance
    # artifact); migration park/adopt marks come from the kill.
    from paddle_tpu import profiler
    from paddle_tpu.serving import RequestTracer
    tracer = RequestTracer(max_completed=4 * max(1, args.requests))
    keep = {}
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                             on_trace_ready=lambda p: None)
    prof.start()
    try:
        traced = run_pass(model, work, n_replicas=3,
                          router=PrefixAffinityRouter(), chaos=True,
                          seed=args.seed, report=report, label="traced",
                          trace=tracer, keep=keep)
    finally:
        prof.stop()
    tdiv = [i for i in range(len(work))
            if traced.get(i) != clean.get(i)]
    assert not tdiv, f"tracing perturbed chaos streams: {tdiv[:10]}"
    migrated_traces = [t for t in tracer.traces()
                       if "park" in t.mark_names()
                       and "adopt" in t.mark_names()]
    assert keep["migrated"] == 0 or migrated_traces, \
        "migrations happened but no trace carries park+adopt marks"
    os.makedirs(os.path.dirname(args.trace_out) or ".", exist_ok=True)
    doc = tracer.export(args.trace_out, include_profiler=True,
                        flight_recorder=keep["timelines"])
    cats = {e.get("cat") for e in doc["traceEvents"]}
    assert "request" in cats and len(cats - {"request", None}) >= 1, \
        f"merged export missing host or request spans: {cats}"
    report["trace_out"] = args.trace_out
    report["traced_migration_traces"] = len(migrated_traces)

    # ---- zero-loss failover: EVERY request bit-identical -------------
    diverged = [i for i in range(len(work)) if chaos.get(i) != clean.get(i)]
    assert not diverged, \
        f"chaos streams diverged from the clean run: {diverged[:10]}"
    assert report["chaos"]["lost"] == 0, report["chaos"]
    assert report["chaos"]["deaths"] == 1, report["chaos"]
    assert report["chaos"]["stalls"] == 1, report["chaos"]
    assert report["chaos"]["migrated"] >= 1, report["chaos"]
    report["bit_identical_requests"] = len(work)

    # single-replica sanity: affinity fleet = single replica tokens too
    # (the routing layer must never change WHAT is generated)
    div1 = [i for i in range(len(work)) if single.get(i) != clean.get(i)]
    assert not div1, f"fleet changed tokens vs single replica: {div1[:10]}"

    # ---- the routing criterion ---------------------------------------
    hits_single = report["single"]["prefix_hits"]
    hits_aff = report["clean"]["prefix_hits"]
    hits_rand = report["random"]["prefix_hits"]
    assert hits_single > 0, report["single"]
    assert hits_aff >= hits_single, \
        f"affinity fleet hit rate fell below the single-replica " \
        f"baseline: {hits_aff} < {hits_single}"
    assert hits_aff > hits_rand, \
        f"affinity routing did not beat random spray: " \
        f"{hits_aff} <= {hits_rand}"

    report["wall_s"] = round(time.perf_counter() - t0, 2)
    print(json.dumps(report))
    # ---- final report through the observability paths (ISSUE 10) -----
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    print(trace_report.report(trace_report.load(args.trace_out)))
    print("== fleet metrics exposition (traced chaos pass) ==")
    print(keep.get("prometheus", ""), end="")
    print("SOAK_FLEET_OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"SOAK_FLEET_FAILED: {e}", file=sys.stderr)
        sys.exit(1)
