"""Measured pipeline-schedule comparison: 1F1B vs ZB-H1 wall-clock.

VERDICT r3 item 3: "measure ZB-H1 for real and close the makespan loop".
Runs the ThreadedFleetExecutor (per-rank threads, jitted stage jobs, each
stage's params pinned to its own virtual CPU device so compute genuinely
overlaps) at pp∈{2,4} × micro∈{4,8} under both schedules, and reports:

  - measured wall-clock makespan (first job start -> last job end)
  - the dependency-simulator makespan fed with the MEASURED mean job
    durations (so the model and the wall clock are directly comparable)
  - the unit-time simulator's predicted bubble reduction

Usage:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/bench_pipeline.py [--write-md]
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def build_stage_jobs(n_stages, hidden=512, layers_per_stage=3, batch=64,
                     seed=0, device_of=None):
    """Per-stage MLP jobs with a HAND-SPLIT backward, the way the
    reference ZB pass splits each matmul_grad into independent dx / dw
    ops sharing saved inputs (pipeline_zero_bubble.py) — no forward
    recompute in either half, so 1F1B and ZB-H1 run identical total
    FLOPs and the measured difference is pure scheduling.

      forward: saves (layer input, layer output) residuals
      B (dx):  per layer g_z = g * (1 - out^2); g = g_z @ W.T  — saves g_z
      W (dw):  per layer dW = x_in.T @ g_z                     — deferred

    Each stage's params are committed to its own virtual CPU device so
    rank threads genuinely overlap."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    rng = np.random.RandomState(seed)

    def stage_fn(params, x):
        for W in params:
            x = jnp.tanh(x @ W)
        return x

    def fwd_resid(params, x):
        resid = []
        for W in params:
            out = jnp.tanh(x @ W)
            resid.append((x, out))
            x = out
        return x, resid

    def bwd_dx(params, resid, g):
        gzs = []
        for W, (xin, out) in zip(reversed(params), reversed(resid)):
            gz = g * (1.0 - out * out)
            gzs.append(gz)
            g = gz @ W.T
        return g, gzs[::-1]

    def bwd_dw(resid, gzs):
        return [xin.T @ gz for (xin, _), gz in zip(resid, gzs)]

    def bwd_full(params, resid, g):
        gx, gzs = bwd_dx(params, resid, g)
        return gx, bwd_dw(resid, gzs)

    # device_of maps stage index -> device slot (ZB-V pins both of a
    # rank's chunks to that rank's device); default = stage index
    dev_of = device_of or (lambda s: s)
    stage_params = []
    for r in range(n_stages):
        Ws = [jnp.asarray(rng.randn(hidden, hidden).astype(np.float32)
                          * (1.0 / np.sqrt(hidden)))
              for _ in range(layers_per_stage)]
        stage_params.append(jax.device_put(Ws, devs[dev_of(r) % len(devs)]))

    def loss_fn(pred, label):
        return ((pred - label) ** 2).mean()

    fwd_jit = jax.jit(fwd_resid)
    dx_jit = jax.jit(bwd_dx)
    dw_jit = jax.jit(bwd_dw)
    full_jit = jax.jit(bwd_full)

    def loss_grad(y, label):
        loss, pull = jax.vjp(lambda yy: loss_fn(yy, label), y)
        (g,) = pull(jnp.ones_like(loss))
        return loss, g
    loss_grad_jit = jax.jit(loss_grad)

    state = {"resid": {}, "gzs": {}, "preds": {},
             "grads": [None] * n_stages, "losses": []}

    def to_dev(v, r):
        return jax.device_put(v, devs[dev_of(r) % len(devs)])

    def fwd(r, m, x):
        out, resid = fwd_jit(stage_params[r], to_dev(x, r))
        state["resid"][(m, r)] = resid
        if r == n_stages - 1:
            state["preds"][m] = out
        out.block_until_ready()
        return out

    def _accum(r, dW):
        g = state["grads"][r]
        state["grads"][r] = dW if g is None else \
            [a + b for a, b in zip(g, dW)]

    def _incoming_cot(r, m, g_or_label):
        if r == n_stages - 1:
            loss, g = loss_grad_jit(state["preds"][m],
                                    to_dev(g_or_label, r))
            state["losses"].append(loss)
            return g
        return to_dev(g_or_label, r)

    def bwd_b_split(r, m, g_or_label):
        g = _incoming_cot(r, m, g_or_label)
        gx, gzs = dx_jit(stage_params[r], state["resid"][(m, r)], g)
        state["gzs"][(m, r)] = gzs
        gx.block_until_ready()
        return gx

    def bwd_w(r, m):
        dW = dw_jit(state["resid"][(m, r)], state["gzs"][(m, r)])
        jax.block_until_ready(dW)
        _accum(r, dW)
        del state["resid"][(m, r)], state["gzs"][(m, r)]

    def bwd_fused(r, m, g_or_label):
        g = _incoming_cot(r, m, g_or_label)
        gx, dW = full_jit(stage_params[r], state["resid"][(m, r)], g)
        gx.block_until_ready()
        _accum(r, dW)
        del state["resid"][(m, r)]
        return gx

    def reset():
        """Clear per-run state so jitted jobs (and their compile caches)
        are reused across repeats — only the first run pays compilation."""
        state["resid"].clear()
        state["gzs"].clear()
        state["preds"].clear()
        state["losses"].clear()
        state["grads"] = [None] * n_stages

    return dict(stage_fn=stage_fn, stage_params=stage_params,
                loss_fn=loss_fn, fwd=fwd, bwd_b_split=bwd_b_split,
                bwd_w=bwd_w, bwd_fused=bwd_fused, state=state,
                reset=reset, hidden=hidden, batch=batch)


def measure(n_stages, n_micro, hidden=1024, layers_per_stage=2, batch=128,
            repeats=2):
    """Wall-clock both schedules; returns a row dict."""
    from paddle_tpu.distributed.fleet_executor import (
        ThreadedFleetExecutor, simulate_pipeline_makespan)

    rng = np.random.RandomState(1)
    xs = [rng.randn(batch, hidden).astype(np.float32)
          for _ in range(n_micro)]
    ys = [rng.randn(batch, hidden).astype(np.float32)
          for _ in range(n_micro)]

    repeats = max(repeats, 1)   # iteration 0 is always jit warmup
    row = {"pp": n_stages, "micro": n_micro}
    for sched, label in (("1F1B", "1f1b"), ("ZB-H1", "zb")):
        best_wall, durs = None, None
        jobs = build_stage_jobs(n_stages, hidden, layers_per_stage, batch)
        for it in range(repeats + 1):
            jobs["reset"]()  # jits persist: only iteration 0 compiles
            if sched in ("ZB-H1",):
                ex = ThreadedFleetExecutor(
                    n_stages, n_micro, sched, jobs["fwd"],
                    jobs["bwd_b_split"], jobs["bwd_w"])
            else:
                ex = ThreadedFleetExecutor(
                    n_stages, n_micro, sched, jobs["fwd"],
                    jobs["bwd_fused"])
            wall = ex.run(xs, ys)
            if it > 0 and (best_wall is None or wall < best_wall):
                best_wall, durs = wall, ex.measured_durations()
        row[f"wall_{label}_ms"] = best_wall * 1e3
        row[f"durs_{label}"] = {k: v * 1e3 for k, v in durs.items()}
        t_f = durs.get("F", 1.0)
        t_b = durs.get("B", 1.0)
        t_w = durs.get("W", max(t_b * 0.5, 1e-9)) if sched == "ZB-H1" \
            else t_b * 0.5  # fused B includes W work; split it nominally
        if sched == "ZB-H1":
            sim = simulate_pipeline_makespan(
                n_stages, n_micro, sched, t_f=t_f, t_b=t_b, t_w=t_w)
        else:
            # fused backward: simulator folds W into B (t_b covers both)
            sim = simulate_pipeline_makespan(
                n_stages, n_micro, sched, t_f=t_f, t_b=t_b * 0.5,
                t_w=t_b * 0.5)
        row[f"sim_{label}_ms"] = sim * 1e3
    row["measured_reduction_pct"] = 100.0 * (
        1.0 - row["wall_zb_ms"] / row["wall_1f1b_ms"])
    u_zb = simulate_pipeline_makespan(n_stages, n_micro, "ZB-H1")
    u_1f = simulate_pipeline_makespan(n_stages, n_micro, "1F1B")
    row["predicted_reduction_pct"] = 100.0 * (1.0 - u_zb / u_1f)
    return row


def measure_zbv(n_ranks, n_micro, hidden=1024, layers_per_stage=1,
                batch=128, repeats=2):
    """ZB-V (2 chunks/rank, V placement) vs the same placement with a
    fused backward — both EXECUTED on the ThreadedZBVExecutor."""
    from paddle_tpu.distributed.fleet_executor import (
        ThreadedZBVExecutor, zbv_stage_of)

    n_stages = 2 * n_ranks
    rank_of = {}
    for r in range(n_ranks):
        for c in (0, 1):
            rank_of[zbv_stage_of(r, c, n_ranks)] = r

    rng = np.random.RandomState(1)
    xs = [rng.randn(batch, hidden).astype(np.float32)
          for _ in range(n_micro)]
    ys = [rng.randn(batch, hidden).astype(np.float32)
          for _ in range(n_micro)]

    from paddle_tpu.distributed.fleet_executor import \
        build_zbv_rank_schedules

    repeats = max(repeats, 1)   # iteration 0 is always jit warmup
    row = {"ranks": n_ranks, "micro": n_micro}
    for split_w, label in ((False, "fused"), (True, "zbv")):
        best_wall, durs, sim = None, None, None
        jobs = build_stage_jobs(n_stages, hidden, layers_per_stage,
                                batch, device_of=lambda s: rank_of[s])
        for it in range(repeats + 1):
            jobs["reset"]()
            ex = ThreadedZBVExecutor(
                n_ranks, n_micro, jobs["fwd"],
                jobs["bwd_b_split"] if split_w else jobs["bwd_fused"],
                jobs["bwd_w"] if split_w else None, split_w=split_w)
            wall = ex.run(xs, ys)
            if it > 0 and (best_wall is None or wall < best_wall):
                best_wall, durs = wall, ex.measured_durations()
                sim = ex.sim_makespan
        row[f"wall_{label}_ms"] = best_wall * 1e3
        row[f"durs_{label}"] = {k: v * 1e3 for k, v in durs.items()}
        row[f"unitsim_{label}"] = sim
        # the dependency model fed with the MEASURED durations — the
        # makespan these jobs imply with true per-rank parallelism (the
        # honest column on a serializing 1-core host)
        if split_w:
            _, msim = build_zbv_rank_schedules(
                n_ranks, n_micro, t_f=durs.get("F", 1.0),
                t_b=durs.get("B", 1.0), t_w=durs.get("W", 1.0))
        else:
            fb = durs.get("B", 1.0)
            _, msim = build_zbv_rank_schedules(
                n_ranks, n_micro, t_f=durs.get("F", 1.0),
                t_b=fb * 0.5, t_w=fb * 0.5, split_w=False)
        row[f"sim_{label}_ms"] = msim * 1e3
    row["measured_reduction_pct"] = 100.0 * (
        1.0 - row["wall_zbv_ms"] / row["wall_fused_ms"])
    row["sim_reduction_pct"] = 100.0 * (
        1.0 - row["sim_zbv_ms"] / row["sim_fused_ms"])
    row["predicted_reduction_pct"] = 100.0 * (
        1.0 - row["unitsim_zbv"] / row["unitsim_fused"])
    return row


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--write-md", action="store_true")
    ap.add_argument("--quick", action="store_true",
                    help="single config (pp=2, micro=4)")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_num_cpu_devices", 8)

    configs = [(2, 4)] if args.quick else [(2, 4), (2, 8), (4, 4), (4, 8)]
    rows = [measure(pp, mi) for pp, mi in configs]
    zbv_configs = [(2, 4)] if args.quick else [(2, 4), (2, 8), (4, 8)]
    zbv_rows = [measure_zbv(p, mi) for p, mi in zbv_configs]
    hdr = ("| pp | micro | wall 1F1B (ms) | wall ZB-H1 (ms) | measured "
           "t_f/t_b/t_w (ms) | sim(measured t) 1F1B | sim(measured t) "
           "ZB-H1 | sim reduction | unit-sim predicted |")
    sep = "|" + "---|" * 9
    lines = [hdr, sep]
    for r in rows:
        d = r["durs_zb"]
        sim_red = 100.0 * (1.0 - r["sim_zb_ms"] / r["sim_1f1b_ms"])
        lines.append(
            f"| {r['pp']} | {r['micro']} | {r['wall_1f1b_ms']:.1f} | "
            f"{r['wall_zb_ms']:.1f} | "
            f"{d.get('F', 0):.1f}/{d.get('B', 0):.1f}/{d.get('W', 0):.1f} | "
            f"{r['sim_1f1b_ms']:.1f} | {r['sim_zb_ms']:.1f} | "
            f"{sim_red:+.1f}% | {r['predicted_reduction_pct']:+.1f}% |")
    table = "\n".join(lines)
    zlines = ["", "ZB-V (2 chunks/rank, V placement) vs fused backward "
              "on the same placement — EXECUTED (ThreadedZBVExecutor):",
              "",
              "| ranks | micro | wall fused (ms) | wall ZB-V (ms) | "
              "wall reduction | sim(measured t) fused | sim(measured t) "
              "ZB-V | sim reduction | unit-sim predicted |",
              "|" + "---|" * 9]
    for r in zbv_rows:
        zlines.append(
            f"| {r['ranks']} | {r['micro']} | {r['wall_fused_ms']:.1f} | "
            f"{r['wall_zbv_ms']:.1f} | {r['measured_reduction_pct']:+.1f}% "
            f"| {r['sim_fused_ms']:.1f} | {r['sim_zbv_ms']:.1f} | "
            f"{r['sim_reduction_pct']:+.1f}% | "
            f"{r['predicted_reduction_pct']:+.1f}% |")
    table = table + "\n" + "\n".join(zlines)
    print(table)
    if args.write_md:
        import os
        ncores = os.cpu_count() or 1
        doc = (
            "# Measured pipeline schedules — 1F1B vs ZB-H1\n\n"
            "Harness: `tools/bench_pipeline.py` — ThreadedFleetExecutor\n"
            "(one thread per pipeline rank, jitted stage jobs, params\n"
            "pinned per virtual CPU device), 2-layer MLP per stage,\n"
            "hidden=1024, batch=128, split backward shares residuals\n"
            "(no recompute) so both schedules run identical total FLOPs.\n\n"
            "Columns: wall = measured first-start..last-end makespan;\n"
            "t_f/t_b/t_w = measured mean job durations (ZB split);\n"
            "sim(measured t) = the dependency-model makespan fed with\n"
            "those measured durations — i.e. what the measured jobs\n"
            "imply when each rank genuinely runs on its own device;\n"
            "unit-sim = the shape-only prediction.\n\n"
            f"HOST CAVEAT: this machine has {ncores} physical core(s).\n"
            "With 1 core, rank threads serialize, so the wall column\n"
            "cannot show bubble overlap (it degenerates to total work,\n"
            "where ZB pays its ~10% two-dispatch split tax). The\n"
            "sim-with-measured-durations column is the makespan evidence\n"
            "those same measured jobs give on parallel hardware; the\n"
            "driver's TPU bench is the real-chip path.\n\n" + table + "\n")
        Path(__file__).resolve().parent.parent.joinpath(
            "BENCH_PIPELINE.md").write_text(doc)
        print("\nwrote BENCH_PIPELINE.md")


if __name__ == "__main__":
    sys.exit(main())
