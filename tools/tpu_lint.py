#!/usr/bin/env python
"""tpu-lint CLI — static trace-safety analysis for Pallas kernels and
traced code (paddle_tpu.analysis; rule catalog in ANALYSIS.md).

Usage:
    python tools/tpu_lint.py [paths...]          # default: paddle_tpu/
    python tools/tpu_lint.py --json paddle_tpu
    python tools/tpu_lint.py --rules A1,A3 paddle_tpu/kernels
    python tools/tpu_lint.py --list-rules

Exit codes: 0 = clean, 1 = findings, 2 = usage error.

The analyzer is loaded straight from paddle_tpu/analysis/ WITHOUT
importing the paddle_tpu package, so no jax import happens: the lint
runs in ~1 s on a cold CPU interpreter and never touches the TPU grant
(run under `env -u PALLAS_AXON_POOL_IPS` anyway — the hosting image's
sitecustomize claims the grant at interpreter startup; `make lint`
does this for you).
"""
import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_analysis():
    """Import paddle_tpu/analysis as a standalone package (bypassing
    paddle_tpu/__init__.py, which imports jax)."""
    pkg_dir = os.path.join(_REPO, "paddle_tpu", "analysis")
    name = "paddle_tpu_analysis_standalone"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpu_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_REPO, "paddle_tpu")],
                    help="files or directories to lint "
                         "(default: paddle_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    ap.add_argument("--rules", default=None, metavar="IDS",
                    help="comma-separated rule ids or slugs "
                         "(e.g. A1,A3 or index-map,vmem)")
    ap.add_argument("--exclude", action="append", default=[],
                    metavar="SUBSTR",
                    help="skip files whose path contains SUBSTR "
                         "(repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    analysis = load_analysis()
    try:
        rules = analysis.select_rules(
            args.rules.split(",") if args.rules else None)
    except ValueError as e:
        print(f"tpu_lint: {e}", file=sys.stderr)
        return 2

    if args.list_rules:
        for r in analysis.all_rules():
            print(f"{r.id:4} [{', '.join(r.slugs)}] ({r.severity}) "
                  f"{r.summary}")
        return 0

    diags, nfiles = analysis.lint_paths(args.paths, rules=rules,
                                        exclude=tuple(args.exclude))

    def pack_of(rule_id):
        # "A3" -> "A", "B2" -> "B"; parse errors group under "parse"
        head = "".join(c for c in rule_id if c.isalpha())
        return head or rule_id

    packs = {}
    for r in rules:
        packs.setdefault(pack_of(r.id), {"rules": [], "findings": 0})
        packs[pack_of(r.id)]["rules"].append(r.id)
    for d in diags:
        packs.setdefault(pack_of(d.rule), {"rules": [], "findings": 0})
        packs[pack_of(d.rule)]["findings"] += 1
    for name, p in packs.items():
        p["files"] = nfiles
        # one assertable line per pack for the driver gate
        p["summary"] = (f"{p['findings']} findings, {nfiles} files, "
                        f"{len(p['rules'])} rules")

    if args.json:
        print(json.dumps({
            "version": 1,
            "files_scanned": nfiles,
            "rules": [r.id for r in rules],
            "packs": packs,
            "findings": [d.to_dict() for d in diags],
        }, indent=2))
    else:
        if diags:
            print(analysis.format_text(diags))
        print(f"tpu-lint: {len(diags)} finding(s) in {nfiles} file(s) "
              f"[rules: {', '.join(r.id for r in rules)}]")
        for name in sorted(packs):
            print(f"tpu-lint[{name}]: {packs[name]['summary']}")
    return 1 if diags else 0


if __name__ == "__main__":
    sys.exit(main())
