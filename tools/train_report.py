"""Offline report over an exported training-monitor document.

Reads the JSON `TrainingMonitor.export()` writes (chrome `traceEvents`
plus the `trainingMonitor` side-channel: step ring, snapshot,
compile-event log) and prints:

* a per-step latency digest (count, p50/p90/p99/max, throughput from
  the token counter);
* a loss / grad-norm trajectory digest (first/last/min/max, NaN'd and
  retraced steps called out — the postmortem view of the ring);
* the compile-event timeline (every trace/retrace/AST rescue/eager
  fallback/program compile with its duration, plus per-kind totals —
  a compile storm reads as a table, not a debugger hunt).

Deliberately stdlib-only: loading this module must never import jax
(every plain `python` start claims the TPU grant — CLAUDE.md), so the
report runs anywhere, including while a trainer holds the chip. The
`--demo` flag is the one exception: it lazily imports paddle_tpu to run
a tiny monitored CPU training loop and write the artifact it then
reports on (`make train-report` smokes exactly that under the
CPU-pinned test env).

Usage:  python tools/train_report.py TRACE.json [--worst 3]
        python tools/train_report.py --demo TRACE.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _percentile(samples, q):
    """Nearest-rank percentile (the serving.metrics rule, duplicated so
    this tool stays import-free)."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def load(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "trainingMonitor" in data:
        return data["trainingMonitor"]
    # a bare snapshot/records dump is accepted too
    return data if isinstance(data, dict) else {"records": data}


# ------------------------------------------------------------- latency
def format_latency(records: List[dict], snapshot: dict) -> str:
    lat = [r["dur_ms"] for r in records
           if isinstance(r.get("dur_ms"), (int, float))]
    lines = [f"steps recorded: {len(records)} "
             f"(#{records[0]['step']}..#{records[-1]['step']}, "
             f"{snapshot.get('steps', '?')} total)"] if records else \
        ["(empty step ring)"]
    if lat:
        lines.append(
            f"  step latency ms: p50 {_percentile(lat, 50):.3f}  "
            f"p90 {_percentile(lat, 90):.3f}  "
            f"p99 {_percentile(lat, 99):.3f}  max {max(lat):.3f}")
        tokens = [r["tokens"] for r in records
                  if isinstance(r.get("tokens"), int) and r.get("dur_ms")]
        if tokens and sum(lat) > 0:
            tps = sum(tokens) / (sum(lat) / 1e3)
            lines.append(f"  throughput: {tps:.1f} tokens/s over the ring")
    return "\n".join(lines)


def format_worst_steps(records: List[dict], n: int = 3) -> str:
    timed = [r for r in records
             if isinstance(r.get("dur_ms"), (int, float))]
    timed.sort(key=lambda r: r["dur_ms"], reverse=True)
    lines = []
    for r in timed[:n]:
        extra = ""
        if r.get("compile_events"):
            extra += "  compile=" + ",".join(
                f"{k}x{v}" for k, v in sorted(r["compile_events"].items()))
        if r.get("nan_hits"):
            extra += f"  NAN_HITS={r['nan_hits']}"
        lines.append(f"  step #{r['step']:<6} {r['dur_ms']:10.3f} ms  "
                     f"loss={_fmt(r.get('loss'))}{extra}")
    return "\n".join(lines) if lines else "  (no timed steps)"


# ---------------------------------------------------------- trajectory
def _fmt(v) -> str:
    if v is None:
        return "-"
    if v != v:                          # NaN
        return "NaN"
    return f"{v:.6g}"


def format_trajectory(records: List[dict], snapshot: dict) -> str:
    lines = []
    for key in ("loss", "grad_norm"):
        vals = [(r["step"], r[key]) for r in records
                if isinstance(r.get(key), (int, float))]
        finite = [(s, v) for s, v in vals if v == v]
        if not vals:
            continue
        row = (f"  {key:<10} first {_fmt(vals[0][1]):>12}  "
               f"last {_fmt(vals[-1][1]):>12}")
        if finite:
            row += (f"  min {_fmt(min(v for _, v in finite)):>12}"
                    f"  max {_fmt(max(v for _, v in finite)):>12}")
        lines.append(row)
        nan_steps = [s for s, v in vals if v != v]
        if nan_steps:
            lines.append(f"      NaN at steps: "
                         f"{' '.join(str(s) for s in nan_steps[:10])}"
                         + (" ..." if len(nan_steps) > 10 else ""))
    retraced = [r["step"] for r in records if r.get("retraced")]
    if retraced:
        lines.append(f"  retraced steps: "
                     f"{' '.join(str(s) for s in retraced[:10])}"
                     + (" ..." if len(retraced) > 10 else ""))
    for k in ("nan_hits", "eager_fallbacks", "retraces"):
        if snapshot.get(k):
            lines.append(f"  ALERT {k} = {snapshot[k]}")
    return "\n".join(lines) if lines else "  (no loss/grad-norm samples)"


# ------------------------------------------------------- compile events
def format_compile_timeline(events: List[dict],
                            counters: Dict[str, int],
                            dropped: int = 0) -> str:
    if not events and not counters:
        return "(no compile events)"
    lines = []
    per_kind: Dict[str, List[float]] = {}
    for e in events:
        per_kind.setdefault(e["kind"], []).append(
            float(e.get("duration_ms") or 0.0))
    lines.append(f"{'kind':<18}{'count':>8}{'logged':>8}{'total(ms)':>12}")
    lines.append("-" * len(lines[0]))
    for kind in sorted(set(counters) | set(per_kind)):
        durs = per_kind.get(kind, [])
        lines.append(f"{kind:<18}{counters.get(kind, 0):>8}"
                     f"{len(durs):>8}{sum(durs):>12.3f}")
    if dropped:
        lines.append(f"(+{dropped} events aged out of the window)")
    t0 = events[0]["t_wall"] if events else 0.0
    for e in events[-20:]:
        dur = (f" {e['duration_ms']:.1f} ms"
               if e.get("duration_ms") is not None else "")
        det = e.get("detail") or {}
        det_s = " ".join(f"{k}={v}" for k, v in det.items())
        lines.append(f"  +{e['t_wall'] - t0:9.3f}s {e['kind']:<16} "
                     f"{e['name']}{dur}  {det_s}".rstrip())
    if len(events) > 20:
        lines.insert(len(lines) - 20,
                     f"  (last 20 of {len(events)} retained events)")
    return "\n".join(lines)


def report(data: dict, worst: int = 3) -> str:
    records = data.get("records") or []
    snapshot = data.get("snapshot") or {}
    parts = ["== step latency ==", format_latency(records, snapshot)]
    parts += [f"== worst {worst} steps ==", format_worst_steps(records, worst)]
    parts += ["== trajectory ==", format_trajectory(records, snapshot)]
    parts += ["== compile events ==",
              format_compile_timeline(
                  data.get("compile_events") or [],
                  data.get("compile_counters") or {},
                  snapshot.get("compile_events_dropped", 0))]
    return "\n".join(parts)


# ------------------------------------------------------------------ demo
def run_demo(path: str) -> None:
    """Tiny monitored CPU training loop -> export artifact at `path`.
    The ONLY jax-importing entry point of this file (opt-in via --demo;
    the make target runs it under the CPU-pinned env)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.profiler import TrainingMonitor

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(64, 64), paddle.nn.ReLU(),
                               paddle.nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(parameters=net.parameters(),
                                 learning_rate=1e-3)

    def train_step(x):
        y = net(x)
        loss = (y * y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(train_step, state_objects=[net, opt])
    rng = np.random.RandomState(0)
    with TrainingMonitor(optimizer=opt, detailed=True).watch(step) as mon:
        for i in range(12):
            # vary the batch once mid-run so the demo shows a retrace
            b = 8 if i < 8 else 16
            x = paddle.to_tensor(rng.rand(b, 64).astype("f"))
            mon.step(step(x), tokens=b)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    mon.export(path)
    print(f"demo training trace written to {path}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="exported TrainingMonitor JSON")
    ap.add_argument("--worst", type=int, default=3,
                    help="how many slowest steps to break down")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny monitored training loop first and "
                         "write the artifact to PATH (imports paddle_tpu)")
    args = ap.parse_args(argv)
    if args.demo:
        run_demo(args.path)
    print(report(load(args.path), worst=args.worst))
    return 0


if __name__ == "__main__":
    sys.exit(main())
