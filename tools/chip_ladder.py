"""BASELINE config-ladder smoke on the real chip.

One training step (fwd+bwd+opt, via the to_static compiled path where
the bench uses it) for each ladder family beyond the flagship Llama
bench: ResNet-50 (ladder 1), ERNIE masked-LM (ladder 2), DiT
(ladder 4, conv+attn mixed), Qwen2-MoE (ladder 5, expert routing).
Proves the model-zoo breadth compiles AND trains on TPU hardware, not
just CPU-interpret. Ladder 3 (Llama) is bench.py itself.
"""
import numpy as np
import jax

import paddle_tpu as paddle

print("devices:", jax.devices())
rng = np.random.RandomState(0)


def train_one(name, model, make_batch, loss_fn):
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def step(*batch):
        loss = loss_fn(model, *batch)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = paddle.jit.to_static(step, state_objects=[model, opt])
    batch = make_batch()
    l0 = float(np.asarray(compiled(*batch)._data))
    l1 = float(np.asarray(compiled(*batch)._data))
    assert np.isfinite(l0) and np.isfinite(l1), (name, l0, l1)
    print(f"LADDER {name}: loss {l0:.4f} -> {l1:.4f} OK", flush=True)


# ladder 1: ResNet-50, CIFAR-like batch
from paddle_tpu.vision.models import resnet50
m = resnet50(num_classes=10)
ce = paddle.nn.CrossEntropyLoss()
train_one(
    "resnet50", m,
    lambda: (paddle.to_tensor(rng.randn(8, 3, 32, 32).astype(np.float32)),
             paddle.to_tensor(rng.randint(0, 10, (8,)))),
    lambda mm, x, y: ce(mm(x), y))

# ladder 2: ERNIE masked-LM step
from paddle_tpu.models.ernie import ernie_tiny, ErnieForMaskedLM
ecfg = ernie_tiny()
em = ErnieForMaskedLM(ecfg)
EV = ecfg.vocab_size


def ernie_loss(mm, ids, labels):
    out = mm(ids)
    logits = out[0] if isinstance(out, (tuple, list)) else out
    return ce(logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))


train_one(
    "ernie_mlm", em,
    lambda: (paddle.to_tensor(rng.randint(1, EV, (4, 64))),
             paddle.to_tensor(rng.randint(1, EV, (4, 64)))),
    ernie_loss)

# ladder 4: DiT (conv+attn mixed)
from paddle_tpu.models.dit import DiT, dit_tiny


def dit_loss(mm, x, t, y):
    # predict-the-input MSE: adaLN-Zero starts the output at exactly 0,
    # so mean(out^2) would be a zero-gradient no-op; a nonzero target
    # makes the step actually move the zero-initialised final layer
    out = mm(x, t, y)
    return ((out.astype("float32") - x.astype("float32")) ** 2).mean()


dcfg = dit_tiny()
dm = DiT(dcfg)
train_one(
    "dit", dm,
    lambda: (paddle.to_tensor(
        rng.randn(2, dcfg.in_channels, dcfg.image_size,
                  dcfg.image_size).astype(np.float32)),
             paddle.to_tensor(rng.randint(0, 1000, (2,))),
             paddle.to_tensor(rng.randint(0, dcfg.num_classes, (2,)))),
    dit_loss)

# ladder 5: Qwen2-MoE causal LM (expert routing + aux loss)
from paddle_tpu.models.qwen2_moe import qwen2_moe_tiny, Qwen2MoeForCausalLM
qcfg = qwen2_moe_tiny()
qm = Qwen2MoeForCausalLM(qcfg)
QV = qcfg.vocab_size


def moe_loss(mm, ids, labels):
    out = mm(ids, labels=labels)
    return out[0] if isinstance(out, (tuple, list)) else out


train_one(
    "qwen2_moe", qm,
    lambda: (paddle.to_tensor(rng.randint(0, QV, (2, 64))),
             paddle.to_tensor(rng.randint(0, QV, (2, 64)))),
    moe_loss)

print("CHIP_LADDER_ALL_OK")
