"""On-chip NUMERIC parity for the Pallas pack (interpret=False).

Execution alone (chip_hour.sh steps) proves Mosaic compiles the
kernels; this asserts the numbers match an XLA reference computed on
the same chip, closing the interpret-mode-only validation gap
(ADVICE r3 medium finding).
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import (
    flash_attention_bshd, flash_attention_varlen_bshd,
    flashmask_attention_bshd)
from paddle_tpu.kernels.paged_attention import paged_attention_decode
print("devices:", jax.devices())


def sdpa_ref(q, k, v, mask=None, causal=True):
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (1.0 / np.sqrt(q.shape[-1]))
    S, Sk = q.shape[1], k.shape[1]
    if causal:
        cm = jnp.tril(jnp.ones((S, Sk), bool))
        s = jnp.where(cm[None, None], s, -1e30)
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def relerr(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return float(np.abs(a - b).max() / (np.abs(b).max() + 1e-9))


# ---- flash fwd + bwd vs SDPA, S=2048 --------------------------------
B, S, H, D = 2, 2048, 4, 128
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
out = flash_attention_bshd(q, k, v, causal=True)
ref = sdpa_ref(q, k, v)
e = relerr(out, ref)
assert e < 3e-2, f"flash fwd parity {e}"
print(f"PARITY flash fwd rel_err={e:.4f} OK")

dq, dk, dv = jax.grad(
    lambda q, k, v: flash_attention_bshd(q, k, v, causal=True)
    .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
rq, rk, rv = jax.grad(
    lambda q, k, v: sdpa_ref(q, k, v).sum(), argnums=(0, 1, 2))(q, k, v)
for name, a, b in [("dq", dq, rq), ("dk", dk, rk), ("dv", dv, rv)]:
    e = relerr(a, b)
    assert e < 5e-2, f"flash bwd {name} parity {e}"
    print(f"PARITY flash bwd {name} rel_err={e:.4f} OK")

# ---- varlen (two packed sequences) vs block-diagonal SDPA -----------
seg = jnp.concatenate([jnp.zeros((B, S // 2), jnp.int32),
                       jnp.ones((B, S // 2), jnp.int32)], axis=1)
out = flash_attention_varlen_bshd(q, k, v, seg, seg, causal=True)
mask = (seg[:, None, :, None] == seg[:, None, None, :])
ref = sdpa_ref(q, k, v, mask=mask)
e = relerr(out, ref)
assert e < 3e-2, f"varlen parity {e}"
print(f"PARITY varlen rel_err={e:.4f} OK")

# ---- flashmask (causal C=1: rows >= start[k] masked) vs dense mask --
start = jnp.asarray(
    rng.randint(1, S + 1, (B, 1, S, 1)).astype(np.int32)
    .clip(min=np.arange(S).reshape(1, 1, S, 1) + 1))
out = flashmask_attention_bshd(q, k, v, start, causal=True)
rows = jnp.arange(S)[:, None]
keep = rows < start[:, 0, :, 0][:, None, :]      # (B, Sq, Sk)
ref = sdpa_ref(q, k, v, mask=keep[:, None], causal=True)
e = relerr(out, ref)
assert e < 3e-2, f"flashmask parity {e}"
print(f"PARITY flashmask rel_err={e:.4f} OK")

# ---- paged decode vs gathered dense attention -----------------------
B2, H2, KVH, D2, page, pps = 4, 8, 8, 128, 16, 8
num_pages = B2 * pps
q1 = jnp.asarray(rng.randn(B2, H2, D2), jnp.bfloat16)
kc = jnp.asarray(rng.randn(num_pages, KVH, page, D2), jnp.bfloat16)
vc = jnp.asarray(rng.randn(num_pages, KVH, page, D2), jnp.bfloat16)
tables = jnp.arange(num_pages, dtype=jnp.int32).reshape(B2, pps)
lens = jnp.full((B2,), page * pps, jnp.int32)
out = paged_attention_decode(q1, kc, vc, tables, lens)
# dense ref: gather pages -> (B, S, KVH, D), single-query attention
kd = kc[tables].transpose(0, 2, 1, 3, 4).reshape(B2, KVH, pps * page, D2)
vd = vc[tables].transpose(0, 2, 1, 3, 4).reshape(B2, KVH, pps * page, D2)
g = H2 // KVH
qf = q1.astype(jnp.float32).reshape(B2, KVH, g, D2)
sc = jnp.einsum("bkgd,bkSd->bkgS", qf, kd.astype(jnp.float32))
sc = sc * (1.0 / np.sqrt(D2))
p = jax.nn.softmax(sc, axis=-1)
ref = jnp.einsum("bkgS,bkSd->bkgd", p, vd.astype(jnp.float32)).reshape(
    B2, H2, D2)
e = relerr(out, ref)
assert e < 3e-2, f"paged parity {e}"
print(f"PARITY paged decode rel_err={e:.4f} OK")

# ---- int8-KV paged decode vs the SAME dense reference (ISSUE 6) -----
# quantize the bf16 cache per (slot, head), run the quantized kernel
# (int8 value pages + fp32 scale pages, dequantize-in-kernel), and
# hold it to the int8 rel-err budget vs the full-precision reference —
# the chip-blind wiring for the next relay window; the CPU interpret
# run of the same code path is pinned by tests/test_serving_quant_kv.
from paddle_tpu.kernels.paged_attention import quantize_kv
kq, ks = quantize_kv(kc)
vq, vs = quantize_kv(vc)
out_q = paged_attention_decode(q1, kq, vq, tables, lens,
                               k_scale=ks, v_scale=vs)
e = relerr(out_q, ref)
assert e < 3e-2, f"int8-KV paged parity {e}"
print(f"PARITY paged decode int8-KV rel_err={e:.4f} OK")

# ---- fused int8 dequant-matmul vs its XLA composition ----------------
# same numerics by construction (fp32 accumulate, per-out-channel
# scale at the flush) — on chip this catches Mosaic lowering bugs the
# interpret-mode CPU tests cannot see; also budgeted against the
# full-precision matmul it approximates (chip_serving measured 0.0065
# for the old route; the fused kernel must hold the same 2e-2 budget).
from paddle_tpu.kernels.quant_matmul import (dequant_matmul_xla,
                                             quant_matmul)
M, K, N = 64, 1024, 1024
w = (rng.randn(K, N) * 0.02).astype(np.float32)
absmax = np.maximum(np.abs(w).max(0), 1e-10)
scale = jnp.asarray((absmax / 127.0).astype(np.float32))
qw = jnp.asarray(np.clip(np.round(w / (absmax / 127.0)[None, :]),
                         -127, 127).astype(np.int8))
x = jnp.asarray(rng.randn(M, K).astype(np.float32))
out_pl = quant_matmul(x, qw, scale)
out_xla = dequant_matmul_xla(x, qw, scale)
e = relerr(out_pl, out_xla)
assert e < 1e-4, f"quant_matmul vs XLA composition {e}"
e_full = relerr(out_pl, np.asarray(x) @ w)
assert e_full < 2e-2, f"quant_matmul vs full precision {e_full}"
print(f"PARITY quant_matmul xla={e:.6f} full={e_full:.4f} OK")

# ---- fused AdamW bucket kernel vs the jnp reference update (ISSUE 9) -
# the flagship recipe: bf16 grads/params, fp32 master, bf16 moments.
# Two checks on chip: (a) the Pallas kernel vs the identical XLA
# composition (same _adamw_math expression — catches Mosaic lowering
# bugs, moments must match bitwise, master within fp32 fusion noise),
# (b) the kernel vs a hand-written jnp AdamW step (independent
# expression, loose fp32 budget).
from paddle_tpu.kernels.fused_optimizer import (adamw_scalars,
                                                fused_adamw_bucket)
rows = 4096
gf = jnp.asarray(rng.randn(rows, 128), jnp.bfloat16)
wf = jnp.asarray(rng.randn(rows, 128), jnp.float32)
sc = adamw_scalars(3e-4, 0.9, 0.999, 1e-8, 0.01, 1)
# bitwise moment check from ZERO-seeded moments (the step-1 shape):
# with m = v = 0 there is no FMA-contraction ambiguity in the moment
# chain, so Mosaic and XLA:TPU must agree bit-for-bit; from nonzero
# moments a contracted `b1*m + omb1*g` can legally differ by 1 fp32
# ulp and flip a bf16 storage bit — that case gets a tolerance below
mz = jnp.zeros((rows, 128), jnp.bfloat16)
vz = jnp.zeros((rows, 128), jnp.bfloat16)
p_pl, w_pl, m_pl, v_pl = fused_adamw_bucket(
    gf, wf, mz, vz, sc, param_dtype=jnp.bfloat16, use_pallas=True)
p_x, w_x, m_x, v_x = fused_adamw_bucket(
    gf, wf, mz, vz, sc, param_dtype=jnp.bfloat16, use_pallas=False)
assert bool(jnp.all(m_pl == m_x)) and bool(jnp.all(v_pl == v_x)), \
    "fused AdamW step-1 moment storage differs from the XLA composition"
e = relerr(w_pl, w_x)
assert e < 1e-5, f"fused AdamW master vs XLA composition {e}"
# steady-state (nonzero moments): FMA-tolerant budgets, plus an
# independent hand-written fp32 reference
mf = jnp.asarray(rng.randn(rows, 128), jnp.bfloat16) * 0.01
vf = jnp.abs(jnp.asarray(rng.randn(rows, 128), jnp.bfloat16)) * 0.01
sc7 = adamw_scalars(3e-4, 0.9, 0.999, 1e-8, 0.01, 7)
p_pl, w_pl, m_pl, v_pl = fused_adamw_bucket(
    gf, wf, mf, vf, sc7, param_dtype=jnp.bfloat16, use_pallas=True)
p_x, w_x, m_x, v_x = fused_adamw_bucket(
    gf, wf, mf, vf, sc7, param_dtype=jnp.bfloat16, use_pallas=False)
for nm, a, b, budget in [("m", m_pl, m_x, 1e-2), ("v", v_pl, v_x, 1e-2),
                         ("w", w_pl, w_x, 1e-5)]:
    es = relerr(a, b)
    assert es < budget, f"fused AdamW steady-state {nm} parity {es}"
g32 = gf.astype(jnp.float32)
m32 = 0.9 * mf.astype(jnp.float32) + 0.1 * g32
v32 = 0.999 * vf.astype(jnp.float32) + 0.001 * g32 * g32
wd = wf * (1.0 - 3e-4 * 0.01)
ref_w = wd - 3e-4 * (m32 / (1 - 0.9 ** 7)) / (
    jnp.sqrt(v32 / (1 - 0.999 ** 7)) + 1e-8)
e2 = relerr(w_pl, ref_w)
assert e2 < 1e-4, f"fused AdamW vs hand reference {e2}"
print(f"PARITY fused_adamw xla={e:.2e} ref={e2:.2e} OK")

print("CHIP_PARITY_ALL_OK")
