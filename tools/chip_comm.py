"""COMM step for the chip hour (ISSUE 12): measured collective ladder.

`profiler/comm.py` accounts what a compiled program MOVES (payload
bytes per mesh axis, read back from the post-SPMD HLO); this step
measures what the interconnect DELIVERS: a psum / all-gather ladder
over the real mesh, timed with `kernels/timing.py::device_time` (the
relay-proof device-side loop — host-side timing over the axon relay
measures the ~7 ms round-trip, not the op), reported as achieved GB/s
against the ACCOUNTED bytes of the very program being timed. The two
legs keep each other honest: the accounting supplies the numerator,
the chip the denominator.

Per rung it prints
    COMM_CHIP <kind> elems=<n> accounted=<payload B> ms=<t> GB/s=<g>
where GB/s = payload / t (logical payload rate; ring all-reduce moves
~2(n-1)/n x payload per link — divide yourself for link-level numbers,
the same honest-reading rule as profiler/comm.py).

Gating (the chip_serving convention): accounting-vs-hand-computed
byte equality is HARD-asserted ON_TPU with >1 device; CPU runs (and a
single-device grant, where a 1-sized axis legitimately emits no
collective) report-only, because the CPU path is covered by the pinned
tests in tests/test_profiler_comm.py and a single chip has nothing to
move. Queued as the COMM step of tools/chip_hour.sh behind the
standing relay gate.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

print("devices:", jax.devices())
ON_TPU = jax.default_backend() == "tpu"

# fp32 elements per rung; payloads 4 MB / 32 MB / 128 MB keep the
# largest all-gather result (x n devices) well under one chip's HBM
LADDER = (1 << 20, 8 << 20, 32 << 20)


def comm_mesh():
    """One flat axis over every visible device — the COMM ladder is an
    interconnect probe, not a parallelism layout."""
    devs = jax.devices()
    return Mesh(np.array(devs), ("x",)), len(devs)


def ladder_fns(mesh):
    """{kind: sharded collective fn} over the mesh's 'x' axis."""
    try:
        from jax import shard_map
    except ImportError:
        from paddle_tpu.jax_compat import shard_map

    def mk(body):
        return jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"),
                                 out_specs=P("x"), check_vma=False))

    return {
        "psum": mk(lambda a: jax.lax.psum(a, "x")),
        "all_gather": jax.jit(shard_map(
            lambda a: jax.lax.all_gather(a, "x", tiled=True), mesh=mesh,
            in_specs=P("x"), out_specs=P(None), check_vma=False)),
    }


def expected_payload(kind, n_elems, n_dev, itemsize=4):
    """Hand-computed payload bytes for one ladder rung — the number the
    IR walk must reproduce (profiler/comm.py payload rule: all-reduce
    at the operand entering it = the PER-SHARD block under shard_map
    (array/n), all-gather at the result it materializes = the full
    array (per-shard operand x group size))."""
    if n_dev <= 1:
        return 0          # a 1-sized axis emits no collective
    full = n_elems * itemsize
    return {"psum": full // n_dev, "all_gather": full}[kind]


def accounted_payload(fn, x, mesh):
    """The profiler.comm accounting of the compiled ladder program."""
    from paddle_tpu.profiler import comm as _comm
    rep = _comm.lowered_comm(fn.lower(x), mesh=mesh)
    return rep.payload_bytes, rep.to_dict()


def main():
    from paddle_tpu.kernels.timing import device_time
    mesh, n_dev = comm_mesh()
    fns = ladder_fns(mesh)
    if n_dev == 1:
        print("COMM_CHIP_SINGLE_DEVICE: 1-device grant — ladder times "
              "the identity program, accounting is honestly 0 bytes "
              "(report-only)")
    failures = []
    for kind, fn in fns.items():
        for n_elems in LADDER:
            x = jax.device_put(
                jnp.ones((n_elems,), jnp.float32),
                NamedSharding(mesh, P("x")))
            want = expected_payload(kind, n_elems, n_dev)
            try:
                got, rep = accounted_payload(fn, x, mesh)
            except Exception as e:               # noqa: BLE001
                got, rep = None, {"error": repr(e)}
            if got != want:
                msg = (f"COMM_ACCOUNT_MISMATCH {kind} elems={n_elems}: "
                       f"accounted={got} expected={want} ({rep})")
                if ON_TPU and n_dev > 1:
                    failures.append(msg)
                print(msg)
            dt = device_time(fn, x, iters=4)
            gbps = (want / dt / 1e9) if (dt == dt and dt > 0 and want) \
                else float("nan")
            print(f"COMM_CHIP {kind} elems={n_elems} accounted={want} "
                  f"ms={dt * 1e3:.3f} GB/s={gbps:.1f}")
    if failures:
        raise AssertionError("; ".join(failures))
    print("COMM_CHIP_OK")


if __name__ == "__main__":
    main()
