"""Publish the SOT-gap inventory (VERDICT r5 #5): run every ladder-model
train step through jit.to_static and commit what fell back to eager and
why (FALLBACKS.md).

`jit.to_static_report()` already collects the data (function-level eager
fallbacks with the breaking error + dy2static's per-reason counters);
this script drives the five BASELINE ladder families through two
compiled steps each — CPU-sized configs, the same model classes the
chip ladder trains — and renders the per-model inventory. An empty
fallback list for a model is the claim "this train step runs as ONE
compiled program"; a populated one is the measured cost of not having a
bytecode tracer, which is exactly the evidence the
build-jit/sot-or-not decision needs (to_static_report docstring).

Usage: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
           python tools/fallback_report.py [--out FALLBACKS.md]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import jit  # noqa: E402

rng = np.random.RandomState(0)
REPORTS = {}


def run_step(name, model, make_batch, loss_fn, steps=2):
    jit.to_static_report(reset=True)
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def step(*batch):
        loss = loss_fn(model, *batch)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = paddle.jit.to_static(step, state_objects=[model, opt])
    t0 = time.perf_counter()
    losses = []
    for _ in range(steps):
        losses.append(float(np.asarray(compiled(*make_batch())._data)))
    dt = time.perf_counter() - t0
    assert all(np.isfinite(l) for l in losses), (name, losses)
    rep = jit.to_static_report(reset=True)
    REPORTS[name] = {"report": rep, "losses": losses, "seconds": dt}
    print(f"{name}: losses {losses} ({dt:.1f}s) "
          f"fallbacks={len(rep['eager_fallbacks'])} "
          f"breaks={rep['break_counters']}", flush=True)


def build_all():
    ce = paddle.nn.CrossEntropyLoss()

    # ladder 1: ResNet-50
    from paddle_tpu.vision.models import resnet50
    m = resnet50(num_classes=10)
    run_step(
        "resnet50", m,
        lambda: (paddle.to_tensor(rng.randn(2, 3, 32, 32).astype(np.float32)),
                 paddle.to_tensor(rng.randint(0, 10, (2,)))),
        lambda mm, x, y: ce(mm(x), y))

    # ladder 2: ERNIE masked-LM
    from paddle_tpu.models.ernie import ernie_tiny, ErnieForMaskedLM
    ecfg = ernie_tiny()
    em = ErnieForMaskedLM(ecfg)
    EV = ecfg.vocab_size

    def ernie_loss(mm, ids, labels):
        out = mm(ids)
        logits = out[0] if isinstance(out, (tuple, list)) else out
        return ce(logits.reshape([-1, logits.shape[-1]]),
                  labels.reshape([-1]))

    run_step(
        "ernie_mlm", em,
        lambda: (paddle.to_tensor(rng.randint(1, EV, (2, 32))),
                 paddle.to_tensor(rng.randint(1, EV, (2, 32)))),
        ernie_loss)

    # ladder 3: Llama causal LM (the flagship bench family)
    from paddle_tpu.models.llama import llama_tiny, LlamaForCausalLM
    lm = LlamaForCausalLM(llama_tiny())

    def lm_loss(mm, ids, labels):
        return mm(ids, labels=labels)

    LV = lm.cfg.vocab_size
    run_step(
        "llama", lm,
        lambda: (paddle.to_tensor(rng.randint(0, LV, (2, 32))),
                 paddle.to_tensor(rng.randint(0, LV, (2, 32)))),
        lm_loss)

    # ladder 4: DiT (conv+attn mixed)
    from paddle_tpu.models.dit import DiT, dit_tiny
    dcfg = dit_tiny()
    dm = DiT(dcfg)

    def dit_loss(mm, x, t, y):
        out = mm(x, t, y)
        return ((out.astype("float32") - x.astype("float32")) ** 2).mean()

    run_step(
        "dit", dm,
        lambda: (paddle.to_tensor(
            rng.randn(2, dcfg.in_channels, dcfg.image_size,
                      dcfg.image_size).astype(np.float32)),
                 paddle.to_tensor(rng.randint(0, 1000, (2,))),
                 paddle.to_tensor(rng.randint(0, dcfg.num_classes, (2,)))),
        dit_loss)

    # ladder 5: Qwen2-MoE (expert routing + aux loss)
    from paddle_tpu.models.qwen2_moe import qwen2_moe_tiny, Qwen2MoeForCausalLM
    qcfg = qwen2_moe_tiny()
    qm = Qwen2MoeForCausalLM(qcfg)
    QV = qcfg.vocab_size

    def moe_loss(mm, ids, labels):
        out = mm(ids, labels=labels)
        return out[0] if isinstance(out, (tuple, list)) else out

    run_step(
        "qwen2_moe", qm,
        lambda: (paddle.to_tensor(rng.randint(0, QV, (2, 32))),
                 paddle.to_tensor(rng.randint(0, QV, (2, 32)))),
        moe_loss)


def _lint_section():
    """FALLBACKS.md section for the dy2static purity diagnostics
    (tpu-lint rule A5, shared Diagnostic type from paddle_tpu.analysis):
    scan/while-lowered bodies that printed at trace time, loops kept
    eager because their bodies mutate non-carried python state, and
    out-of-trace collective rejections — recorded at runtime while the
    ladder steps above compiled, reported next to the eager-fallback
    counts they explain. See ANALYSIS.md for the rule catalog."""
    lines = ["", "## dy2static purity diagnostics (tpu-lint A5, `--lint`)",
             "",
             "Runtime promotions of the purity checks: recorded while "
             "the ladder train steps compiled (shared `Diagnostic` type "
             "with `tools/tpu_lint.py`; catalog in ANALYSIS.md).", ""]
    any_diag = False
    for name, d in REPORTS.items():
        diags = d["report"].get("purity_diagnostics", [])
        if not diags:
            continue
        any_diag = True
        lines.append(f"### {name}")
        for dg in diags:
            lines.append(
                f"- `{dg['rule']}[{dg['slug']}]` {dg['path']}:{dg['line']} "
                f"— {dg['message']}")
        lines.append("")
    if not any_diag:
        lines.append("No purity diagnostics: every compiled ladder step "
                     "ran without trace-time side effects, eager-kept "
                     "mutating loops, or out-of-trace collectives.")
    return lines


def write_md(path, lint=False):
    lines = [
        "# FALLBACKS.md — the eager-fallback inventory "
        "(jit.to_static_report)", "",
        "Two compiled train steps per BASELINE ladder model on the "
        "8-virtual-CPU test platform; for each, every function-level "
        "eager fallback `to_static` recorded (with the error that broke "
        "it) plus dy2static's per-reason break/decline counters. "
        "Regenerate with `tools/fallback_report.py` (VERDICT r5 #5).", "",
        "An empty row = the whole step (fwd+bwd+AdamW) ran as one "
        "compiled program. `break_counters` counts CONVERSION decisions "
        "(e.g. a scan decline that still compiled via while_loop or "
        "unrolling is a counter, not a fallback).", "",
        "| ladder model | step losses | eager fallbacks | break counters |",
        "|---|---|---|---|",
    ]
    detail = []
    for name, d in REPORTS.items():
        rep = d["report"]
        fbs = rep["eager_fallbacks"]
        losses = ", ".join(f"{l:.4f}" for l in d["losses"])
        bc = ", ".join(f"{k}={v}" for k, v in
                       sorted(rep["break_counters"].items())) or "—"
        lines.append(f"| {name} | {losses} | {len(fbs)} | {bc} |")
        if fbs:
            detail.append(f"## {name}")
            for fb in fbs:
                detail.append(f"- `{fb.get('function', '?')}`: "
                              f"{fb.get('reason', fb)}")
            detail.append("")
    if detail:
        lines += ["", "## Per-function fallback reasons", ""] + detail
    else:
        lines += ["", "No ladder-model train step produced a "
                  "function-level eager fallback: the five families "
                  "compile end-to-end. The break counters above are the "
                  "only dy2static activity (conversions that still "
                  "landed in a compiled form)."]
    if lint:
        lines += _lint_section()
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "FALLBACKS.md"))
    ap.add_argument("--lint", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="include the dy2static purity-diagnostic section "
                         "(tpu-lint A5 runtime promotions; on by default "
                         "so a plain regeneration keeps the committed "
                         "FALLBACKS.md section — --no-lint to drop it)")
    args = ap.parse_args()
    build_all()
    write_md(args.out, lint=args.lint)
