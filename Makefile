# Dev workflow. CPU tests run on an 8-device virtual mesh; PALLAS_AXON_POOL_IPS
# is unset so python startup skips the axon TPU claim (sitecustomize would
# otherwise block every interpreter on the single TPU grant).
TEST_ENV = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu

.PHONY: test test-fast bench lint

test:
	$(TEST_ENV) python -m pytest tests/ -x -q

test-fast:
	$(TEST_ENV) python -m pytest tests/ -x -q -m "not slow"

bench:
	python bench.py
