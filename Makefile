# Dev workflow. CPU tests run on an 8-device virtual mesh; PALLAS_AXON_POOL_IPS
# is unset so python startup skips the axon TPU claim (sitecustomize would
# otherwise block every interpreter on the single TPU grant).
TEST_ENV = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu

.PHONY: test test-fast bench soak soak-fleet soak-fleet-proc soak-disagg lint train-report dist-report

# tpu-lint: static trace-safety analysis (ANALYSIS.md). AST-only — no
# jax import, no TPU grant, ~1 s; gates `make test`.
lint:
	$(TEST_ENV) python tools/tpu_lint.py paddle_tpu

test: lint
	$(TEST_ENV) python -m pytest tests/ -x -q
	# slow-marked TP + multi-decode serving identity variants
	# (pytest.ini's addopts deselect them; the explicit -m opts back
	# in — tier-1 stays lean, the full gate still proves int8/wq
	# identity under TP and int8/snapshot identity under decode_steps)
	$(TEST_ENV) python -m pytest tests/test_serving_tp.py \
		tests/test_serving_multi.py -m slow -q
	# slow-marked cross-process/compile-cache/http secondary variants
	# (ISSUE 14; tier-1 keeps the probe-gated lifecycle + the named
	# integrity paths, the full gate runs the rest)
	$(TEST_ENV) python -m pytest tests/test_fleet_proc.py \
		tests/test_compile_cache.py tests/test_fleet_http.py \
		-m slow -q
	# tier-1 870s budget (PR 14): the heavy convergence/zoo smoke and
	# the routing-criterion mini-soak moved behind the slow marker —
	# the full gate still runs every one of them here
	$(TEST_ENV) python -m pytest tests/test_dit.py \
		tests/test_vision_zoo.py tests/test_loop_grad.py \
		tests/test_fleet_router.py -m slow -q

test-fast: lint
	$(TEST_ENV) python -m pytest tests/ -x -q -m "not slow"

bench:
	python bench.py

# Randomized fault-injection soak of the serving engine (ISSUE 3 + 15
# + 17): the 200-request acceptance run (multi-LoRA clean+chaos passes
# via --lora, tiered-KV spill off/clean/chaos via --spill) + extra
# seeds. CPU-only, minutes-bounded; excluded from tier-1 via the
# `slow` marker (pytest.ini addopts).
soak:
	$(TEST_ENV) python tools/soak_serving.py --requests 200 --seed 0 --lora --spill
	# trace-report smoke (ISSUE 10): re-read the trace the soak's
	# traced pass exported (stdlib-only, but TEST_ENV anyway — every
	# plain python start claims the TPU grant)
	$(TEST_ENV) python tools/trace_report.py profiler_log/soak_trace.json
	$(TEST_ENV) python -m pytest tests/test_soak_serving.py -m slow -q

# Training-observability smoke (ISSUE 11): run a tiny monitored CPU
# training loop (--demo: trace + mid-run retrace), export the
# TrainingMonitor document, and re-read it with the stdlib-only
# reporter — OBSERVABILITY.md's end-to-end example.
train-report:
	$(TEST_ENV) python tools/train_report.py --demo profiler_log/train_trace.json

# Distributed-observability smoke (ISSUE 12): run a tiny threaded ZB
# pipeline, export one chrome-trace per rank (with a live comm_report
# riding along), then merge them with the stdlib-only reporter — the
# cross-process layout exercised in-process.
dist-report:
	$(TEST_ENV) python tools/dist_report.py --demo profiler_log \
	  --out profiler_log/dist_merged.json

# Multi-replica fleet chaos soak (ISSUE 7): seeded kill + stall of
# replicas mid-stream; zero-loss / bit-identity / routing criteria.
# CPU-only, minutes-bounded; excluded from tier-1 like `make soak`.
soak-fleet:
	$(TEST_ENV) python tools/soak_fleet.py --requests 120 --seed 0
	# trace-report smoke over the MERGED (host spans + request rows)
	# chrome trace the traced chaos pass exported
	$(TEST_ENV) python tools/trace_report.py profiler_log/soak_fleet_trace.json
	$(TEST_ENV) python -m pytest tests/test_soak_fleet.py -m slow -q

# Cross-process fleet chaos soak (ISSUE 14): real worker processes over
# the TCPStore mailbox — seeded kill -9 mid-stream, a permanently wedged
# worker, a slow-heartbeat worker, wire drop/duplicate, the cold-vs-warm
# compile-cache bench (>= 5x) and a rolling restart. 3 seeds.
soak-fleet-proc:
	$(TEST_ENV) python tools/soak_fleet.py --procs --requests 30 --seed 0
	$(TEST_ENV) python -m pytest tests/test_soak_fleet_proc.py -m slow -q

# Disaggregated prefill/decode chaos soak (ISSUE 18): role-split fleet
# with mid-flight KV handoff — prefill kill -9 with the kv_page stream
# half shipped, decode death mid-adopt, relay stalls with capped-backoff
# re-pulls, role-starved co-location fallback, the decode-TPOT
# comparison against chunked-prefill co-location, and the int8-KV
# variant. 3 chaos seeds inside the ladder.
soak-disagg:
	$(TEST_ENV) python tools/soak_fleet.py --disagg --requests 64 --seed 0
	$(TEST_ENV) python -m pytest tests/test_soak_fleet_disagg.py -m slow -q

# Sanitizer builds of the native extension (parity: reference
# SANITIZER_TYPE configure option). Runs the native test suite against an
# ASan/TSan build of the C++ TCPStore + shm ring.
sanitize-address:
	g++ -O1 -g -fPIC -shared -std=c++17 -fsanitize=address \
	  -I/usr/local/include/python3.12 \
	  paddle_tpu/_native/src/paddle_tpu_native.cc \
	  -o /tmp/_paddle_tpu_native_asan.so -lpthread -lrt
	@echo "ASan build OK: /tmp/_paddle_tpu_native_asan.so"

sanitize-thread:
	g++ -O1 -g -fPIC -shared -std=c++17 -fsanitize=thread \
	  -I/usr/local/include/python3.12 \
	  paddle_tpu/_native/src/paddle_tpu_native.cc \
	  -o /tmp/_paddle_tpu_native_tsan.so -lpthread -lrt
	@echo "TSan build OK: /tmp/_paddle_tpu_native_tsan.so"
