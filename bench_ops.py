"""Op-level microbenchmarks: prove (or disprove) the XLA-fusion story.

VERDICT r2 #2: the A.2 fused-kernel backlog (fused_rope, rms_norm,
swiglu, fused_dropout_add, gemm epilogue — reference
`paddle/phi/kernels/fusion/gpu/`) was covered by "XLA will fuse it" with
zero measurements. This harness measures, on the live chip:

  - Pallas flash attention vs an XLA-composed SDPA (fwd and fwd+bwd)
  - the elementwise/fusion pack (rms_norm[+residual], rope, swiglu,
    fused_dropout_add, bias+gelu epilogue) as achieved HBM bandwidth vs
    the device roofline — a memory-bound op whose XLA composition runs
    near the roofline needs no hand-written kernel (>10% gap = Pallas
    candidate, per the round-3 plan)
  - paged-KV decode attention GB/s vs HBM peak
  - int8 weight-only dequant matmul vs bf16 matmul in the decode regime

Usage: python bench_ops.py [--write-md] [--quick] [-k N] [--spread-pct P]
Prints one JSON line per benchmark; --write-md also rewrites
BENCH_OPS.md. Never exits non-zero; a watchdog prints partial results if
the transport wedges (same rationale as bench.py).

Timing robustness (VERDICT r5 #7): every number is the MEDIAN of k
(default 3) independent device_time measurements, reported with a
`spread_pct` column ((max-min)/median over the freshest k draws); when
the spread exceeds --spread-pct (default 20%), the sample is
automatically re-measured with k more draws (up to --max-reruns extra
rounds) — the median is then over everything collected, while the
spread tracks the freshest round so a single relay hiccup is clearable
and can no longer masquerade as a kernel regression. Rows whose final
spread still exceeds the threshold carry "noisy": true so the table
regeneration can flag them (the rope-row contradiction in BENCH_OPS.md
was exactly such a one-shot artifact).
"""
from __future__ import annotations

import argparse
import json
import os
import threading
import time

import numpy as np

RESULTS = []
DEADLINE_S = int(os.environ.get("BENCH_OPS_DEADLINE_S", "600"))
# timing policy (overridden by CLI flags in main())
TIMING = {"k": 3, "spread_pct": 20.0, "max_reruns": 2}

# per-chip rooflines (bf16 FLOP/s, HBM bytes/s)
PEAKS = {
    "v5e": (197e12, 819e9), "v5 lite": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6e": (918e12, 1640e9), "trillium": (918e12, 1640e9),
    "cpu": (1e12, 100e9),
}


def _peaks(device_kind):
    kind = device_kind.lower()
    for k, v in PEAKS.items():
        if k in kind:
            return v
    return PEAKS["v5e"]


def _watchdog():
    time.sleep(DEADLINE_S)
    _emit_all(error="bench_ops watchdog fired (transport wedged?)")
    os._exit(0)


def _emit_all(error=None):
    for r in RESULTS:
        print(json.dumps(r), flush=True)
    if error:
        print(json.dumps({"bench": "__status__", "error": error}), flush=True)


def _device_time(fn, *args, iters=10):
    """Relay-proof device-side timing; see kernels/timing.py for the
    full methodology (fori_loop chaining, fetch sync, 2N-N
    differencing, NaN sentinel for unresolvably fast ops). Indirection
    point: the CPU harness test monkeypatches THIS name."""
    from paddle_tpu.kernels.timing import device_time
    return device_time(fn, *args, iters=iters)


def _host_time(fn, *args, iters=10):
    """Wall-clock timing for host<->device transfer paths (the tiered-KV
    promote copy), which cannot ride the fori_loop device chain. fn MUST
    end with a host fetch (np.asarray of an element that depends on the
    transfer) — that fetch is the only real synchronization over the
    axon relay; jax.block_until_ready does NOT block there. Indirection
    point: the CPU harness test monkeypatches THIS name."""
    fn(*args)                                # warm-up (first-touch paths)
    t0 = time.perf_counter()
    for _ in range(iters):
        fn(*args)
    return (time.perf_counter() - t0) / iters


def _time_stats(fn, *args, iters=10, timer=None):
    """Median-of-k timing with spread + auto-rerun (module docstring).

    The median is over EVERY draw collected, but the rerun exit spread
    is over the freshest k only — a single relay hiccup in round 1 must
    not make the threshold unsatisfiable (the whole point of rerunning
    is to let tight re-draws clear it). Returns (median_seconds,
    spread_fraction of the freshest k). NaN sentinels from any draw
    poison the whole sample to NaN (an op that sometimes fails to
    resolve is not trustworthy at all). `timer` defaults to the
    device-side chain; transfer benches pass _host_time."""
    samples = []
    rounds = 0
    while True:
        for _ in range(TIMING["k"]):
            dt = (timer or _device_time)(fn, *args, iters=iters)
            if not (dt > 0):
                return float("nan"), float("nan")
            samples.append(dt)
        med = float(np.median(samples))
        fresh = samples[-TIMING["k"]:]
        spread = (max(fresh) - min(fresh)) / med if med > 0 else 0.0
        if spread * 100.0 <= TIMING["spread_pct"] or \
                rounds >= TIMING["max_reruns"]:
            return med, spread
        rounds += 1


def _record(name, variant, shape, dt, flops=None, bytes_moved=None,
            device_kind="?", spread=None):
    fpeak, bpeak = _peaks(device_kind)
    if isinstance(dt, tuple):       # (median, spread) from _time_stats
        dt, spread = dt
    if not (dt > 0):        # NaN sentinel from _time_stats
        rec = {"bench": name, "variant": variant, "shape": shape,
               "ms": None, "device": device_kind,
               "note": "unresolved: 2N-N delta <= 0 at the loop cap"}
        RESULTS.append(rec)
        return rec
    rec = {"bench": name, "variant": variant, "shape": shape,
           "ms": round(dt * 1e3, 4), "device": device_kind}
    if spread is not None and spread == spread:
        rec["spread_pct"] = round(spread * 100.0, 1)
        if spread * 100.0 > TIMING["spread_pct"]:
            rec["noisy"] = True     # still unstable after the reruns
    if flops:
        rec["tflops"] = round(flops / dt / 1e12, 2)
        rec["mfu"] = round(flops / dt / fpeak, 4)
    if bytes_moved:
        rec["gbps"] = round(bytes_moved / dt / 1e9, 1)
        rec["hbm_frac"] = round(bytes_moved / dt / bpeak, 4)
    RESULTS.append(rec)
    return rec


# ---------------------------------------------------------------- benches
def bench_flash_vs_sdpa(dev, quick):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.flash_attention import flash_attention_bshd

    def xla_sdpa(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, v.dtype.type(1) * k) \
            * (1.0 / np.sqrt(q.shape[-1]))
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask, s, -1e9)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    if dev == "cpu":          # interpret-mode Pallas: harness check only
        shapes = [(1, 256, 2, 64)]
    elif quick:
        shapes = [(4, 2048, 16, 64)]
    else:
        shapes = [(4, 2048, 16, 64), (1, 8192, 16, 64)]
    for B, S, H, D in shapes:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
        flops_fwd = 4.0 * B * H * S * S * D * 0.5  # causal halves the work
        flash = jax.jit(lambda q, k, v: flash_attention_bshd(
            q, k, v, causal=True))
        sdpa = jax.jit(xla_sdpa)
        for variant, fn in [("pallas_flash", flash), ("xla_sdpa", sdpa)]:
            dt = _time_stats(fn, q, k, v)
            _record("attention_fwd", variant, f"b{B}s{S}h{H}d{D}", dt,
                    flops=flops_fwd, device_kind=dev)
        # fwd+bwd
        for variant, fn in [("pallas_flash", flash), ("xla_sdpa", sdpa)]:
            g = jax.jit(jax.grad(lambda q, k, v: fn(q, k, v).astype(
                jnp.float32).sum(), argnums=(0, 1, 2)))
            dt = _time_stats(g, q, k, v)
            _record("attention_fwdbwd", variant, f"b{B}s{S}h{H}d{D}", dt,
                    flops=flops_fwd * 3.5, device_kind=dev)


def bench_fusion_pack(dev, quick):
    """The A.2 backlog as roofline fractions: each op is memory-bound;
    bytes = reads + writes of the major arrays (bf16)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.incubate.nn.functional import (
        fused_rms_norm, fused_rotary_position_embedding, swiglu,
        fused_dropout_add)

    if dev == "cpu":
        B, S, Hd = (1, 256, 512)
    else:
        B, S, Hd = (4, 2048, 4096) if quick else (8, 4096, 4096)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, Hd), jnp.bfloat16)
    res = jnp.asarray(rng.randn(B, S, Hd), jnp.bfloat16)
    w = jnp.asarray(rng.randn(Hd), jnp.bfloat16)
    nbytes = x.size * 2

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    def t(a):
        return Tensor(a)

    # no-residual fused_rms_norm returns a single Tensor (no [0]!
    # after the arity fix a [0] would batch-slice and let XLA DCE
    # 7/8 of the work)
    rms = jax.jit(lambda a: fused_rms_norm(t(a), t(w))._data)
    _record("rms_norm", "xla_fused", f"{B}x{S}x{Hd}",
            _time_stats(rms, x), bytes_moved=2 * nbytes, device_kind=dev)
    # the Pallas counterpart (kernels/fused_norm.py), same wall-clock
    # harness as the xla_fused row above so the two are comparable —
    # kept so every table regeneration re-checks the A.2 call (on-chip
    # verdict: XLA at least matches Pallas for rms_norm at every shape
    # tried, so the model keeps the XLA composition)
    from paddle_tpu.kernels.fused_norm import rms_norm_rows
    rms_pl = jax.jit(lambda a: rms_norm_rows(
        a.reshape(-1, Hd), w.astype(a.dtype)).reshape(a.shape))
    _record("rms_norm", "pallas", f"{B}x{S}x{Hd}",
            _time_stats(rms_pl, x), bytes_moved=2 * nbytes, device_kind=dev)

    rms_res = jax.jit(
        lambda a, r: fused_rms_norm(t(a), t(w), residual=t(r))[0]._data)
    _record("rms_norm_residual", "xla_fused", f"{B}x{S}x{Hd}",
            _time_stats(rms_res, x, res), bytes_moved=3 * nbytes,
            device_kind=dev)

    # rope on (B, S, H, D)
    H, D = (4, 64) if dev == "cpu" else (32, 128)
    qk = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    pos = jnp.arange(S)
    inv = 1.0 / (10000 ** (jnp.arange(0, D, 2) / D))
    ang = pos[:, None] * inv[None, :]
    sin = jnp.sin(ang).astype(jnp.bfloat16)[None, :, None, :]
    cos = jnp.cos(ang).astype(jnp.bfloat16)[None, :, None, :]
    def _rope_call(a):
        out = fused_rotary_position_embedding(t(a), sin=t(sin), cos=t(cos))
        return (out[0] if isinstance(out, (tuple, list)) else out)._data

    rope = jax.jit(_rope_call)
    _record("rope", "xla_fused", f"{B}x{S}x{H}x{D}",
            _time_stats(rope, qk), bytes_moved=2 * qk.size * 2,
            device_kind=dev)

    inter = 512 if dev == "cpu" else (11008 if not quick else 4096)
    g1 = jnp.asarray(rng.randn(B * S // 4, inter), jnp.bfloat16)
    g2 = jnp.asarray(rng.randn(B * S // 4, inter), jnp.bfloat16)
    sw = jax.jit(lambda a, b: swiglu(t(a), t(b))._data)
    _record("swiglu", "xla_fused", f"{B * S // 4}x{inter}",
            _time_stats(sw, g1, g2), bytes_moved=3 * g1.size * 2,
            device_kind=dev)

    da = jax.jit(lambda a, b: fused_dropout_add(t(a), t(b), p=0.0,
                                                training=False)._data)
    _record("dropout_add", "xla_fused", f"{B}x{S}x{Hd}",
            _time_stats(da, x, res), bytes_moved=3 * nbytes, device_kind=dev)

    # gemm epilogue: matmul + bias + gelu fused by XLA — compute-bound
    if dev == "cpu":
        M, K, N = (256, 256, 256)
    else:
        M, K, N = (4096, 4096, 4096) if not quick else (2048, 2048, 2048)
    a = jnp.asarray(rng.randn(M, K), jnp.bfloat16)
    wt = jnp.asarray(rng.randn(K, N), jnp.bfloat16)
    bias = jnp.asarray(rng.randn(N), jnp.bfloat16)
    ep = jax.jit(lambda a, w_, b_: jax.nn.gelu(a @ w_ + b_))
    plain = jax.jit(lambda a, w_: a @ w_)
    dt_ep, sp_ep = _time_stats(ep, a, wt, bias)
    dt_pl, sp_pl = _time_stats(plain, a, wt)
    _record("gemm_epilogue", "matmul_bias_gelu", f"{M}x{K}x{N}", dt_ep,
            flops=2.0 * M * K * N, device_kind=dev, spread=sp_ep)
    _record("gemm_epilogue", "matmul_only", f"{M}x{K}x{N}", dt_pl,
            flops=2.0 * M * K * N, device_kind=dev, spread=sp_pl)
    if dt_ep > 0 and dt_pl > 0:     # NaN sentinel would poison the JSON
        RESULTS.append({"bench": "gemm_epilogue", "variant": "overhead_pct",
                        "value": round(100 * (dt_ep - dt_pl) / dt_pl, 2),
                        "device": dev})


def bench_paged_decode(dev, quick):
    """bf16 vs int8 KV pages (ISSUE 6): the decode kernel is
    bandwidth-bound at the HBM roofline, so bytes/token IS tokens/s at
    fixed HBM. Each page size gets a bf16 row, an int8 row (quantized
    caches + per-slot scale pages, dequantize-in-kernel), a static
    `int8_kv_bytes_ratio` decision row (bf16/int8 bytes per token —
    the >= ~1.7x acceptance number; < 2.0 exactly because the fp32
    scales ride along), and a measured `int8_decode_speedup_pct` row."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.paged_attention import (
        alloc_paged_cache, paged_attention_decode, paged_page_bytes,
        quantize_kv)

    if dev == "cpu":
        B, KVH, H, D = 2, 2, 4, 64
        pages, S = (16,), 64
    else:
        B, KVH, H, D = 16, 8, 32, 128
        # 16 = vLLM-style small pages (DMA-latency-bound even folded),
        # 128 = TPU-preferred page size (near the big-page roofline)
        pages, S = (16, 128), 1024 if quick else 2048
    rng = np.random.RandomState(0)
    for page in pages:
        pages_per_seq = S // page
        num_pages = B * pages_per_seq
        k_cache, v_cache = alloc_paged_cache(KVH, num_pages, page, D,
                                             dtype=jnp.bfloat16)
        k_cache = jnp.asarray(rng.randn(*k_cache.shape), jnp.bfloat16)
        v_cache = jnp.asarray(rng.randn(*v_cache.shape), jnp.bfloat16)
        bt = jnp.arange(num_pages, dtype=jnp.int32).reshape(
            B, pages_per_seq)
        sl = jnp.full((B,), S, jnp.int32)
        q = jnp.asarray(rng.randn(B, H, D), jnp.bfloat16)
        fn = jax.jit(lambda q, kc, vc, bt=bt, sl=sl: paged_attention_decode(
            q, kc, vc, bt, sl))
        dt_bf = _time_stats(fn, q, k_cache, v_cache)
        # bytes via the capacity math's single source (page_size=1 ==
        # per-token bytes), so the bench can never drift from the
        # engine's accounting if the scale layout changes
        kv_bytes = B * S * paged_page_bytes(KVH, 1, D)        # bf16 K+V
        _record("paged_decode", f"pallas_page{page}",
                f"b{B}s{S}kvh{KVH}h{H}d{D}", dt_bf,
                bytes_moved=kv_bytes, device_kind=dev)

        # int8 image of the SAME cache contents (per-slot quantization)
        kq, ks = quantize_kv(k_cache)
        vq, vs = quantize_kv(v_cache)
        fn_q = jax.jit(
            lambda q, kc, vc, kss, vss, bt=bt, sl=sl:
            paged_attention_decode(q, kc, vc, bt, sl,
                                   k_scale=kss, v_scale=vss))
        dt_i8 = _time_stats(fn_q, q, kq, vq, ks, vs)
        kv_bytes_i8 = B * S * paged_page_bytes(KVH, 1, D, "int8")
        _record("paged_decode", f"pallas_int8_page{page}",
                f"b{B}s{S}kvh{KVH}h{H}d{D}", dt_i8,
                bytes_moved=kv_bytes_i8, device_kind=dev)
        RESULTS.append({
            "bench": "paged_decode",
            "variant": f"int8_kv_bytes_ratio_page{page}",
            "value": round(kv_bytes / kv_bytes_i8, 3),
            "device": dev})
        dt_bf, dt_i8 = dt_bf[0], dt_i8[0]
        if dt_bf > 0 and dt_i8 > 0:
            RESULTS.append({
                "bench": "paged_decode",
                "variant": f"int8_decode_speedup_pct_page{page}",
                "value": round(100 * (dt_bf - dt_i8) / dt_bf, 2),
                "device": dev})


def bench_paged_decode_tp(dev, quick):
    """Sharded paged-decode bandwidth (ISSUE 8): the decode kernel at
    TP in {1, 2, 4} over the hybrid mesh's 'model' axis, reported as
    BYTES-TRUE per-chip GB/s — one step still reads every live token's
    K/V, but the pages are head-sharded so each chip moves
    global_bytes / tp (paged_page_bytes is the bytes source, same as
    the engine's accounting). Degrees beyond the device count (or not
    dividing KVH) emit an explicit skip row instead of silently
    shrinking coverage. On CPU the GSPMD path partitions the
    interpret-mode kernel (the virtual-mesh validation); on TPU the
    shard_map manual path runs the real kernel per shard."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.kernels.paged_attention import (
        paged_attention_decode, paged_attention_decode_tp,
        paged_page_bytes)

    if dev == "cpu":
        B, KVH, H, D, page, S = 2, 4, 8, 64, 8, 64
    else:
        B, KVH, H, D, page, S = 16, 8, 32, 128, 128, 1024 if quick else 2048
    rng = np.random.RandomState(0)
    pages_per_seq = S // page
    num_pages = B * pages_per_seq
    devs = jax.devices()
    kv_bytes_global = B * S * paged_page_bytes(KVH, 1, D)
    for tp in (1, 2, 4):
        if tp > len(devs) or KVH % tp:
            RESULTS.append({
                "bench": "paged_decode_tp", "variant": f"tp{tp}",
                "device": dev,
                "note": f"skipped: {len(devs)} device(s), KVH={KVH}"})
            continue
        k_cache = jnp.asarray(
            rng.randn(num_pages, KVH, page, D), jnp.bfloat16)
        v_cache = jnp.asarray(
            rng.randn(num_pages, KVH, page, D), jnp.bfloat16)
        q = jnp.asarray(rng.randn(B, H, D), jnp.bfloat16)
        bt = jnp.arange(num_pages, dtype=jnp.int32).reshape(
            B, pages_per_seq)
        sl = jnp.full((B,), S, jnp.int32)
        if tp == 1:
            fn = jax.jit(lambda q, kc, vc, bt=bt, sl=sl:
                         paged_attention_decode(q, kc, vc, bt, sl))
        else:
            mesh = Mesh(np.asarray(devs[:tp], dtype=object).reshape(
                1, 1, 1, 1, tp),
                ("data", "pipe", "sharding", "sep", "model"))
            shard = NamedSharding(mesh, P(None, "model", None, None))
            k_cache = jax.device_put(k_cache, shard)
            v_cache = jax.device_put(v_cache, shard)
            q = jax.device_put(
                q, NamedSharding(mesh, P(None, "model", None)))
            fn = jax.jit(lambda q, kc, vc, bt=bt, sl=sl, mesh=mesh:
                         paged_attention_decode_tp(q, kc, vc, bt, sl,
                                                   mesh))
        dt = _time_stats(fn, q, k_cache, v_cache)
        # bytes-true per-chip traffic: head-sharded pages split the
        # global K/V read exactly by tp
        per_chip = kv_bytes_global // tp
        _record("paged_decode_tp", f"tp{tp}_page{page}",
                f"b{B}s{S}kvh{KVH}h{H}d{D}", dt,
                bytes_moved=per_chip, device_kind=dev)
        RESULTS.append({
            "bench": "paged_decode_tp",
            "variant": f"tp{tp}_bytes_per_chip",
            "value": per_chip, "device": dev})


def bench_multi_decode(dev, quick):
    """Multi-step device-side decode (ISSUE 13): K decode iterations of
    a small Llama inside ONE compiled launch (`forward_paged_decode_multi`
    — in-graph sampling, per-step paged cache writes through the scan
    carry) vs K single-step launches. Rows per K in {1, 4, 8, 16}:
    wall ms, BYTES-TRUE KV GB/s (each step reads the then-current
    prefix and writes one token — paged_page_bytes is the accounting
    source, same as the engine's), derived tokens/s, and an
    `amortization_pct` row = how much of K single-step launches the
    K-step launch saves (host launch overhead + per-launch readback
    amortized xK). A `default_k` decision row picks the measured-best
    K for the next relay window's engine default."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.autograd import no_grad
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.kernels.paged_attention import (alloc_paged_cache,
                                                    paged_page_bytes)
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if dev == "cpu":
        B, S, page = 2, 48, 8
        cfg = LlamaConfig(vocab_size=128, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=128)
    else:
        # quick halves the model depth and prefix length like the
        # sibling benches — 4 multi-decode jit compiles are the cost
        B, S, page = 8, (512 if quick else 1024), 128
        cfg = LlamaConfig(vocab_size=8192, hidden_size=1024,
                          intermediate_size=2816,
                          num_hidden_layers=4 if quick else 8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=4096)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if dev != "cpu":
        model.bfloat16()
    state = {k: t._data for k, t in model.state_dict().items()}
    wdtype = next(a.dtype for a in state.values()
                  if jnp.issubdtype(a.dtype, jnp.floating))
    D = cfg.hidden_size // cfg.num_attention_heads
    KVH = cfg.num_key_value_heads
    ks = (1, 4, 8, 16)
    # room for S prefix tokens + the largest K per row, plus pad page 0
    pages_per_seq = -(-(S + max(ks)) // page)
    num_pages = B * pages_per_seq + 1
    rng = np.random.RandomState(0)
    caches = [tuple(jnp.asarray(rng.randn(*a.shape) * 0.1, a.dtype)
                    for a in alloc_paged_cache(KVH, num_pages, page, D,
                                               dtype=wdtype))
              for _ in range(cfg.num_hidden_layers)]
    flat0 = [a for kv in caches for a in kv]
    arity = len(caches[0])
    bt = jnp.asarray(
        1 + np.arange(B * pages_per_seq, dtype=np.int32).reshape(
            B, pages_per_seq))
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    sl = jnp.full((B,), S, jnp.int32)
    eos = jnp.full((B,), -1, jnp.int32)
    key = jax.random.PRNGKey(0)
    kv_tok = paged_page_bytes(KVH, 1, D, str(wdtype)) \
        * cfg.num_hidden_layers

    # device_time spreads *args as plain arrays: state and caches ride
    # flattened positionally (a closure would bake ~100 MB of weights
    # into the program as literals)
    state_keys = sorted(state)
    sargs = [state[k] for k in state_keys]

    def make(K):
        caps = jnp.full((B,), K, jnp.int32)

        def prog(ids_a, sl_a, key_a, *rest):
            sv, flat = rest[:len(state_keys)], rest[len(state_keys):]
            st = {k: Tensor(v) for k, v in zip(state_keys, sv)}
            pc = [tuple(Tensor(a)
                        for a in flat[i * arity:(i + 1) * arity])
                  for i in range(cfg.num_hidden_layers)]
            with no_grad():
                toks, n_emit, ok, _ = functional_call(
                    model, st, Tensor(ids_a), pc, Tensor(bt),
                    Tensor(sl_a), Tensor(caps), Tensor(eos), key_a,
                    method="forward_paged_decode_multi", k_steps=K)
            return toks._data, n_emit._data, ok._data

        return jax.jit(prog)

    shape = (f"b{B}s{S}l{cfg.num_hidden_layers}h{cfg.hidden_size}"
             f"page{page}")
    times = {}
    for K in ks:
        fn = make(K)
        dt = _time_stats(fn, ids, sl, key, *sargs, *flat0)
        # bytes-true per launch: step j reads B rows' (S + j)-token
        # prefix and writes one token per row, scales included
        nbytes = sum(B * (S + j) * kv_tok + B * kv_tok
                     for j in range(K))
        rec = _record("multi_decode", f"k{K}", shape, dt,
                      bytes_moved=nbytes, device_kind=dev)
        times[K] = dt[0]
        if dt[0] > 0:
            RESULTS.append({
                "bench": "multi_decode", "variant": f"tok_s_k{K}",
                "value": round(B * K / dt[0], 1), "device": dev})
    if times.get(1, 0) > 0:
        for K in ks[1:]:
            if times.get(K, 0) > 0:
                # launch-overhead amortization: K single-step launches
                # vs one K-step launch
                save = 100 * (K * times[1] - times[K]) / (K * times[1])
                RESULTS.append({
                    "bench": "multi_decode",
                    "variant": f"amortization_pct_k{K}",
                    "value": round(save, 2), "device": dev})
        best = max((K for K in ks if times.get(K, 0) > 0),
                   key=lambda K: B * K / times[K])
        RESULTS.append({"bench": "multi_decode", "variant": "default_k",
                        "value": best, "device": dev})


def bench_lora_matmul(dev, quick):
    """Multi-LoRA segment-bmm (ISSUE 15): the per-launch adapter-delta
    GEMM at N_adapters in {1, 4, 16} x rank in {8, 16, 64}. Each row's
    slot stack holds the N loaded adapters (+ the null slot), rows
    spread round-robin across them — the masked kernel streams every
    loaded adapter's A/B once per launch, so the N sweep measures
    exactly what serving N adapters costs over serving one. Bytes-true
    via `lora_delta_bytes` (active adapters' weights + x + delta). The
    `n_adapter_vs_solo_pct` decision row per rank = 100 x t(N=1) /
    t(N=16): the ISSUE-15 acceptance bar is >= 70 (the N-adapter step
    at >= 0.7x the single-adapter step). That bar is a CHIP number:
    on CPU the kernel runs in interpret mode, where every extra slot
    adds python-loop grid steps, so the CPU row wildly understates the
    ratio (the engine-level CPU probe in tools/chip_serving.py, which
    measures whole serving steps, lands at ~solo parity) — same
    harness-evidence-only caveat as bench_multi_decode's CPU rows."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.lora_matmul import (lora_delta_bytes,
                                                lora_matmul,
                                                lora_matmul_xla,
                                                pick_lora_blocks)

    B, H, N = (8, 256, 256) if dev == "cpu" else (16, 4096, 4096)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    n_adapters = (1, 4, 16)
    ranks = (8, 16, 64)
    times = {}
    for R in ranks:
        for NA in n_adapters:
            S = NA + 1                       # + the null slot
            a = jnp.asarray(rng.randn(S, H, R) * 0.02, jnp.float32)
            b = jnp.asarray(rng.randn(S, R, N) * 0.02, jnp.float32)
            # slot 0 is the all-zero null adapter (the engine contract)
            a = a.at[0].set(0.0)
            b = b.at[0].set(0.0)
            ids = jnp.asarray(1 + np.arange(B) % NA, jnp.int32)
            blocks = pick_lora_blocks(B, H, R, N)
            if blocks is not None:
                fn = jax.jit(lambda xx, ii, aa, bb, _blk=blocks:
                             lora_matmul(xx, ii, aa, bb, blocks=_blk))
                variant = f"pallas_n{NA}_r{R}"
            else:                            # fallback shapes still row
                fn = jax.jit(lora_matmul_xla)
                variant = f"xla_n{NA}_r{R}"
            dt = _time_stats(fn, x, ids, a, b)
            # bytes-true: the masked kernel streams EVERY slot in the
            # stack (null slot included), re-streaming A/x once per
            # output block column — the accounting follows the grid
            bn = blocks[1] if blocks is not None else None
            nbytes = lora_delta_bytes(B, H, R, N, S, bn=bn)
            _record("lora_matmul", variant, f"b{B}x{H}x{N}", dt,
                    bytes_moved=nbytes, device_kind=dev)
            times[(NA, R)] = dt[0]
        t1, t16 = times.get((1, R), 0), times.get((16, R), 0)
        if t1 > 0 and t16 > 0:
            RESULTS.append({
                "bench": "lora_matmul",
                "variant": f"n_adapter_vs_solo_pct_r{R}",
                "value": round(100 * t1 / t16, 2), "device": dev})


def bench_int8_matmul(dev, quick):
    """The int8-vs-bf16 DECISION sweep (VERDICT r5 #7): weight-only
    int8 halves the weight traffic but pays a dequant; whether that
    wins depends on the batch M (decode M=1 is pure weight-bound,
    prefill-sized M amortizes the weights). One row per M plus a
    speedup_pct decision row, so the first live window settles which
    serving regimes should quantize."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn.quant import weight_quantize, weight_only_linear
    import paddle_tpu as paddle

    K, N = (256, 256) if dev == "cpu" else (4096, 4096)
    rng = np.random.RandomState(0)
    w = paddle.to_tensor(rng.randn(K, N).astype(np.float32) * 0.02)
    qw, scale = weight_quantize(w, algo="weight_only_int8")
    w_bf = w._data.astype(jnp.bfloat16)

    int8 = jax.jit(lambda xa: weight_only_linear(
        paddle.Tensor(xa), qw, weight_scale=scale,
        weight_dtype="int8")._data)
    bf16 = jax.jit(lambda xa: xa @ w_bf)
    for M in (1, 32, 256):
        x_bf = jnp.asarray(rng.randn(M, K), jnp.bfloat16)
        dt_i8, sp_i8 = _time_stats(int8, x_bf)
        dt_bf, sp_bf = _time_stats(bf16, x_bf)
        _record("weight_only_matmul", "int8", f"{M}x{K}x{N}", dt_i8,
                bytes_moved=K * N, device_kind=dev, spread=sp_i8)
        _record("weight_only_matmul", "bf16", f"{M}x{K}x{N}", dt_bf,
                bytes_moved=K * N * 2, device_kind=dev, spread=sp_bf)
        if dt_i8 > 0 and dt_bf > 0:
            RESULTS.append({
                "bench": "weight_only_matmul",
                "variant": f"int8_speedup_pct_m{M}",
                "value": round(100 * (dt_bf - dt_i8) / dt_bf, 2),
                "device": dev})


def bench_optimizer_update(dev, quick):
    """Bytes-true AdamW update rows (ISSUE 9): the round-4 chip point
    is ~21 ms for 608M fp32 states == the HBM roofline, so the update
    is pure bytes and GB/s IS the metric. One row per state recipe —
    fp32 moments (the round-4 configuration), bf16 moments through the
    per-leaf XLA path, and the fused bucketed Pallas kernel — each
    with bytes from kernels.fused_optimizer.adamw_update_bytes (the
    engine's single accounting source), plus decision rows: the static
    bf16 bytes ratio, the measured fused-vs-XLA speedup, and each
    recipe's projected ms for the 608M-param flagship state at the
    measured GB/s (directly comparable to the 21 ms chip point)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.fused_optimizer import (
        LANES, adamw_scalars, adamw_update_bytes, fused_adamw_bucket)

    rows = 256 if dev == "cpu" else (32768 if quick else 131072)
    E = rows * LANES
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(rows, LANES), jnp.bfloat16)
    w = jnp.asarray(rng.randn(rows, LANES), jnp.float32)   # fp32 master
    scalars = adamw_scalars(3e-4, 0.9, 0.999, 1e-8, 0.01, 100)

    def make(mdtype, use_pallas):
        m = jnp.zeros((rows, LANES), mdtype)
        v = jnp.zeros((rows, LANES), mdtype)
        fn = jax.jit(lambda g, w, m, v: fused_adamw_bucket(
            g, w, m, v, scalars, param_dtype=jnp.bfloat16,
            use_pallas=use_pallas))
        return fn, m, v

    variants = [
        ("xla_fp32_moments", jnp.float32, False),
        ("xla_bf16_moments", jnp.bfloat16, False),
        ("fused_pallas_bf16_moments", jnp.bfloat16, True),
    ]
    times = {}
    for name, mdtype, use_pallas in variants:
        fn, m, v = make(mdtype, use_pallas)
        nbytes = adamw_update_bytes(
            E, param_width=2, moment_width=jnp.dtype(mdtype).itemsize,
            has_master=True)
        dt = _time_stats(fn, g, w, m, v)
        times[name] = (dt[0], nbytes)
        _record("optimizer_update", name, f"{E}elems", dt,
                bytes_moved=nbytes, device_kind=dev)
        if dt[0] > 0:
            # projected flagship time: the 608M-param AdamW state at
            # this recipe's measured GB/s (round-4 chip point: ~21 ms)
            flag_bytes = adamw_update_bytes(
                608_000_000, param_width=2,
                moment_width=jnp.dtype(mdtype).itemsize, has_master=True)
            RESULTS.append({
                "bench": "optimizer_update",
                "variant": f"projected_608M_ms_{name}",
                "value": round(flag_bytes / (nbytes / dt[0]) * 1e3, 2),
                "device": dev})
    b32 = adamw_update_bytes(E, param_width=2, moment_width=4,
                             has_master=True)
    b16 = adamw_update_bytes(E, param_width=2, moment_width=2,
                             has_master=True)
    RESULTS.append({"bench": "optimizer_update",
                    "variant": "bf16_state_bytes_ratio",
                    "value": round(b32 / b16, 3), "device": dev})
    dt_xla = times["xla_bf16_moments"][0]
    dt_fused = times["fused_pallas_bf16_moments"][0]
    if dt_xla > 0 and dt_fused > 0:
        RESULTS.append({"bench": "optimizer_update",
                        "variant": "fused_vs_xla_speedup_pct",
                        "value": round(100 * (dt_xla - dt_fused) / dt_xla, 2),
                        "device": dev})


def bench_kv_spill(dev, quick):
    """Tiered-KV promotion path (ISSUE 17): wall-clock host->device rate
    of the engine's promote copy — CRC-checked payload decode plus one
    `.at[pid].set(jnp.asarray(...))` commit per layer array, ending in
    the single-element fetch that is the only honest sync over the
    relay — for ONE radix page's full K/V stack at page in {64, 128} x
    {bf16, int8} (int8 rows carry their fp32 scale rows, the engine's
    payload layout). The `promote_vs_recompute` decision row projects
    the measured bf16 page-128 rate onto a 7B-class stack
    (L=32, KVH=8, D=128) against recomputing those 128 tokens of
    prefill at 40% MFU on this chip's peak: value = t_recompute /
    t_promote, > 1 means promotion wins and the spill tier pays."""
    import jax.numpy as jnp
    from paddle_tpu.serving.kv_cache import (decode_page_payload,
                                             encode_page_payload)

    rng = np.random.RandomState(0)
    L, KVH, D = (2, 2, 64) if dev == "cpu" else (4, 8, 128)
    NUM_PAGES = 4
    rates = {}
    for page in (64, 128):
        for dtype in ("bf16", "int8"):
            kvs, scales = [], []
            for _ in range(L):
                if dtype == "int8":
                    kvs.append(rng.randint(
                        -127, 128, (page, KVH, D)).astype(np.int8))
                    kvs.append(rng.randint(
                        -127, 128, (page, KVH, D)).astype(np.int8))
                    scales.append(rng.rand(page, KVH).astype(np.float32))
                    scales.append(rng.rand(page, KVH).astype(np.float32))
                else:
                    kvs.append(rng.randn(page, KVH, D)
                               .astype(jnp.bfloat16))
                    kvs.append(rng.randn(page, KVH, D)
                               .astype(jnp.bfloat16))
            arrays = kvs + scales
            payload = encode_page_payload(arrays)
            nbytes = sum(a.nbytes for a in arrays)
            caches = [jnp.zeros((NUM_PAGES,) + a.shape, a.dtype)
                      for a in arrays]

            def promote(payload=payload, caches=caches):
                arrs = decode_page_payload(payload)
                out = None
                for c, a in zip(caches, arrs):
                    out = c.at[1].set(jnp.asarray(a))
                return np.asarray(out[1].ravel()[0])   # fetch sync

            med, sp = _time_stats(promote, timer=_host_time)
            _record("kv_spill", f"promote_{dtype}_page{page}",
                    f"L{L}x{page}x{KVH}x{D}", (med, sp),
                    bytes_moved=nbytes, device_kind=dev)
            if med > 0:
                rates[(page, dtype)] = nbytes / med
    if (128, "bf16") in rates:
        page_bytes_7b = 32 * 2 * 128 * 8 * 128 * 2     # L*2*P*KVH*D*2B
        t_promote = page_bytes_7b / rates[(128, "bf16")]
        fpeak, _ = _peaks(dev)
        t_recompute = 2 * 7e9 * 128 / (0.4 * fpeak)
        RESULTS.append({"bench": "kv_spill",
                        "variant": "promote_vs_recompute",
                        "value": round(t_recompute / t_promote, 2),
                        "device": dev})


BENCHES = [bench_flash_vs_sdpa, bench_fusion_pack, bench_paged_decode,
           bench_paged_decode_tp, bench_multi_decode, bench_lora_matmul,
           bench_int8_matmul, bench_optimizer_update, bench_kv_spill]


def write_md(path="BENCH_OPS.md"):
    dev = next((r.get("device") for r in RESULTS if r.get("device")), "?")
    lines = [
        "# Op microbenchmarks (bench_ops.py)", "",
        f"Device: **{dev}**. Roofline fractions use bf16 peak FLOP/s and "
        "HBM peak bytes/s for the chip; `hbm_frac` near 1.0 means the "
        "XLA-fused composition saturates memory bandwidth and needs no "
        "hand-written kernel (>10% gap = Pallas candidate).", "",
        f"Timing: median of k={TIMING['k']} device_time draws; "
        "`spread%` = (max-min)/median, auto-rerun above "
        f"{TIMING['spread_pct']}% (bench_ops.py docstring); rows still "
        "noisy after the reruns are marked `!`.", "",
        "| bench | variant | shape | ms | spread% | TFLOP/s | MFU "
        "| GB/s | HBM frac |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in RESULTS:
        if r.get("bench") == "__status__" or "ms" not in r:
            continue
        ms = "unresolved" if r["ms"] is None else r["ms"]
        sp = r.get("spread_pct", "")
        if r.get("noisy"):
            sp = f"{sp} !"
        lines.append(
            f"| {r['bench']} | {r['variant']} | {r.get('shape','')} "
            f"| {ms} | {sp} | {r.get('tflops','')} | {r.get('mfu','')} "
            f"| {r.get('gbps','')} | {r.get('hbm_frac','')} |")
    # decision rows AND skip notes: a degree skipped for lack of
    # devices must be visible in the table regeneration, not silently
    # absent (the bench_paged_decode_tp coverage contract)
    extra = [r for r in RESULTS
             if "value" in r or ("note" in r and "ms" not in r)]
    if extra:
        lines.append("")
        for r in extra:
            lines.append(f"- {r['bench']}/{r['variant']}: "
                         f"{r.get('value', r.get('note'))}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _build_parser():
    ap = argparse.ArgumentParser(
        prog="bench_ops.py",
        description="Op-level TPU microbenchmarks. Every number is the "
                    "median of k independent device-side timings with a "
                    "spread percentage column; samples whose spread exceeds "
                    "--spread-pct are automatically re-measured (k more "
                    "draws, up to --max-reruns rounds) before the median "
                    "is taken — see the module docstring.")
    ap.add_argument("--quick", action="store_true",
                    help="smaller shapes / fewer configs")
    ap.add_argument("--write-md", action="store_true",
                    help="rewrite BENCH_OPS.md from the results")
    ap.add_argument("-k", type=int, default=TIMING["k"],
                    help="timing samples per measurement (median-of-k, "
                         "default %(default)s)")
    ap.add_argument("--spread-pct", type=float,
                    default=TIMING["spread_pct"],
                    help="(max-min)/median spread above which a sample "
                         "is re-measured (default %(default)s%%)")
    ap.add_argument("--max-reruns", type=int, default=TIMING["max_reruns"],
                    help="extra measurement rounds before accepting a "
                         "noisy sample (default %(default)s)")
    return ap


def main():
    try:
        # parse_known_args: an unknown flag must not exit(2) — the
        # driver's contract is that bench scripts never exit non-zero
        args, _ = _build_parser().parse_known_args()
    except SystemExit as e:
        if e.code in (0, None):          # --help: argparse printed it
            return
        # bad flag VALUE (-k abc): keep the one-JSON-line contract —
        # a silent empty exit 0 would read as a clean run
        _emit_all(error=f"bench_ops: bad command line (argparse exit "
                        f"{e.code}); run with --help")
        return
    TIMING["k"] = max(1, args.k)
    TIMING["spread_pct"] = args.spread_pct
    TIMING["max_reruns"] = max(0, args.max_reruns)
    threading.Thread(target=_watchdog, daemon=True).start()
    quick = args.quick
    try:
        import jax
        dev = getattr(jax.devices()[0], "device_kind",
                      jax.devices()[0].platform)
    except Exception as e:
        _emit_all(error=f"device init failed: {e!r}")
        return
    for bench in BENCHES:
        try:
            bench(dev, quick)
        except Exception as e:
            RESULTS.append({"bench": bench.__name__,
                            "error": repr(e)[:300]})
    _emit_all()
    if args.write_md:
        write_md()


if __name__ == "__main__":
    main()
