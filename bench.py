"""Benchmark: Llama decoder pretraining step on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
metric = Llama pretraining MFU (the BASELINE.md north star is >= 40% MFU);
vs_baseline = MFU / 0.40. Also reports tokens/sec/chip inside the line's
extra fields for the record.
"""
from __future__ import annotations

import json
import time

import numpy as np


def peak_flops_per_chip(device_kind: str) -> float:
    """bf16 peak FLOP/s per chip by device kind."""
    kind = device_kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6": 918e12, "v6e": 918e12, "trillium": 918e12,
        "cpu": 1e12,  # nominal, CPU fallback is correctness-only
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 197e12


def llama_step_flops(cfg, batch, seq):
    """Training FLOPs/step: 6*N*tokens (fwd+bwd) + attention 12*L*s^2*h."""
    # The input-embedding lookup performs no matmul FLOPs; only the LM
    # head's vocab matmul counts toward the 6*N model.
    n_matmul = (
        cfg.vocab_size * cfg.hidden_size  # LM head
        + cfg.num_hidden_layers * (
            2 * cfg.hidden_size * cfg.hidden_size  # q,o
            + 2 * cfg.hidden_size * (cfg.num_key_value_heads *
                                     cfg.hidden_size // cfg.num_attention_heads)
            + 3 * cfg.hidden_size * cfg.intermediate_size))
    n_params = n_matmul + (0 if cfg.tie_word_embeddings
                           else cfg.vocab_size * cfg.hidden_size)
    tokens = batch * seq
    dense = 6.0 * n_matmul * tokens
    attn = 12.0 * cfg.num_hidden_layers * batch * seq * seq * cfg.hidden_size
    return dense + attn, n_params


def run(use_pallas=True, shrink=0):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.nn.functional.flash_attention import sdp_kernel
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    with sdp_kernel(enable_flash=bool(use_pallas)):
        return _run_inner(paddle, LlamaConfig, LlamaForCausalLM, jax,
                          use_pallas, shrink)


def _run_inner(paddle, LlamaConfig, LlamaForCausalLM, jax, use_pallas, shrink):
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu and shrink:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=12,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=2048)
        batch, seq, iters = 2, 2048, 6
    elif on_tpu:
        # ~0.8B-param config that fits one v5e chip (16GB HBM) with AdamW
        # fp32 states + bf16 params/activations.
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4096, num_hidden_layers=18,
                          num_attention_heads=12, num_key_value_heads=12,
                          max_position_embeddings=2048)
        batch, seq, iters = 4, 2048, 6
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256)
        batch, seq, iters = 2, 128, 3

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters(),
                                 multi_precision=on_tpu)

    def train_step(ids, labels):
        loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(train_step, state_objects=[model, opt])

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # warmup (compile)
    loss = step(ids, labels)
    loss._data.block_until_ready()
    step(ids, labels)._data.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    loss._data.block_until_ready()
    dt = (time.perf_counter() - t0) / iters

    flops, n_params = llama_step_flops(cfg, batch, seq)
    tokens_per_s = batch * seq / dt
    peak = peak_flops_per_chip(getattr(dev, "device_kind", dev.platform))
    mfu = flops / dt / peak

    return {
        "metric": "llama_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.40, 4),
        "tokens_per_sec_per_chip": round(tokens_per_s, 1),
        "step_time_s": round(dt, 4),
        "n_params": int(n_params),
        "loss": float(np.asarray(loss._data)),
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "attention": "pallas_flash" if use_pallas else "xla_sdpa",
        "config": {"hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
                   "batch": batch, "seq": seq},
    }


def main():
    """Never exits non-zero: tries the Pallas flash path, then the XLA sdpa
    fallback, then a smaller config, and as a last resort reports the error
    inside a well-formed JSON line."""
    import traceback

    attempts = [
        {"use_pallas": True, "shrink": 0},
        {"use_pallas": False, "shrink": 0},
        {"use_pallas": True, "shrink": 1},
        {"use_pallas": False, "shrink": 1},
    ]
    errors = []
    for kw in attempts:
        try:
            result = run(**kw)
            if errors:
                result["recovered_from"] = errors[-1][:300]
            print(json.dumps(result))
            return
        except Exception:
            errors.append(traceback.format_exc().strip().split("\n")[-1])
    print(json.dumps({
        "metric": "llama_pretrain_mfu", "value": 0.0,
        "unit": "fraction_of_peak", "vs_baseline": 0.0,
        "error": "; ".join(e[:200] for e in errors[-2:]),
    }))


if __name__ == "__main__":
    main()
