"""Benchmark: Llama decoder pretraining step on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
metric = Llama pretraining MFU (the BASELINE.md north star is >= 40% MFU);
vs_baseline = MFU / 0.40. Also reports tokens/sec/chip inside the line's
extra fields for the record.

Hang-proof by construction: the default entrypoint is a SUPERVISOR that
never initializes a jax backend (sitecustomize registers the axon PJRT
plugin in every python process, but the single-client TPU grant is only
claimed at the first jax operation — register_plugin just installs a
factory — and the supervisor performs none). It re-execs this file with
--worker under a hard wall-clock budget (BENCH_DEADLINE_S, default 720s)
and re-prints the worker's best JSON line; on timeout it terminates the
worker (SIGTERM before SIGKILL — a SIGKILLed TPU client leaks the grant)
and prints a structured error JSON instead. The worker additionally runs
a watchdog thread (fires 60s before the supervisor's deadline) so a
wedged TPU transport — e.g. jax.devices() blocking forever on a dead
axon relay, which produced rc=124 in round 2 — still yields a JSON line
and exit 0.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

def _deadline_s() -> int:
    try:
        v = int(float(os.environ.get("BENCH_DEADLINE_S", "720")))
    except (TypeError, ValueError):
        v = 720
    # Floor keeps the worker watchdog strictly before the supervisor's
    # deadline AND its margin >= 240s (CLAUDE.md: TPU calls need generous
    # timeouts; a 0.8B to_static compile can legitimately take minutes).
    return max(v, 300)


DEADLINE_S = _deadline_s()


def peak_flops_per_chip(device_kind: str) -> float:
    """bf16 peak FLOP/s per chip by device kind."""
    kind = device_kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6": 918e12, "v6e": 918e12, "trillium": 918e12,
        "cpu": 1e12,  # nominal, CPU fallback is correctness-only
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 197e12


def llama_step_flops(cfg, batch, seq):
    """Training FLOPs/step: 6*N*tokens (fwd+bwd) + attention 12*L*s^2*h."""
    # The input-embedding lookup performs no matmul FLOPs; only the LM
    # head's vocab matmul counts toward the 6*N model.
    n_matmul = (
        cfg.vocab_size * cfg.hidden_size  # LM head
        + cfg.num_hidden_layers * (
            2 * cfg.hidden_size * cfg.hidden_size  # q,o
            + 2 * cfg.hidden_size * (cfg.num_key_value_heads *
                                     cfg.hidden_size // cfg.num_attention_heads)
            + 3 * cfg.hidden_size * cfg.intermediate_size))
    n_params = n_matmul + (0 if cfg.tie_word_embeddings
                           else cfg.vocab_size * cfg.hidden_size)
    tokens = batch * seq
    dense = 6.0 * n_matmul * tokens
    attn = 12.0 * cfg.num_hidden_layers * batch * seq * seq * cfg.hidden_size
    return dense + attn, n_params, attn


def run(use_pallas=True, shrink=0, fused_opt=False):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.nn.functional.flash_attention import sdp_kernel
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    with sdp_kernel(enable_flash=bool(use_pallas)):
        return _run_inner(paddle, LlamaConfig, LlamaForCausalLM, jax,
                          use_pallas, shrink, fused_opt)


def _run_inner(paddle, LlamaConfig, LlamaForCausalLM, jax, use_pallas, shrink,
               fused_opt=False):
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu and shrink:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=12,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=2048)
        batch, seq, iters = 2, 2048, 6
    elif on_tpu:
        # ~0.8B-param config that fits one v5e chip (16GB HBM) with AdamW
        # fp32 states + bf16 params/activations.
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4096, num_hidden_layers=18,
                          num_attention_heads=12, num_key_value_heads=12,
                          max_position_embeddings=2048)
        batch, seq, iters = 4, 2048, 6
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256)
        batch, seq, iters = 2, 128, 3

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    # fused_opt (ISSUE 9): bf16 moments + the fused bucketed Pallas
    # update — the ~21 ms/608M AdamW roofline is pure state bytes, so
    # this is the one lever left on the flagship. The attempt chain
    # falls back to the eager per-leaf update if the fused kernel
    # misbehaves on chip (chip-blind staging).
    opt = paddle.optimizer.AdamW(
        3e-4, parameters=model.parameters(), multi_precision=on_tpu,
        fused=bool(fused_opt),
        moment_dtype="bfloat16" if fused_opt and on_tpu else None)

    def train_step(ids, labels):
        loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(train_step, state_objects=[model, opt])

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # warmup (compile). NOTE: over the axon relay block_until_ready does
    # not actually block — only a host fetch synchronizes (measured in
    # bench_ops.py::_time_stats). Fetch the loss scalar to sync, and time
    # two loop lengths so differencing cancels the ~66 ms round-trip +
    # fetch overhead; the donated to_static state chains step N+1 on
    # step N, so the steps themselves cannot overlap or be elided.
    loss = step(ids, labels)
    float(np.asarray(loss._data))
    float(np.asarray(step(ids, labels)._data))

    def timed(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = step(ids, labels)
        float(np.asarray(loss._data))
        return time.perf_counter() - t0, loss

    t_short, loss = timed(2)
    t_long, loss = timed(2 + iters)
    timing = "differenced"
    for _ in range(2):
        if t_long > t_short:
            break
        # noise inversion (relay hiccup): retry rather than fabricate
        # a near-zero dt and an impossible MFU
        t_short, loss = timed(2)
        t_long, loss = timed(2 + iters)
    if t_long > t_short:
        dt = (t_long - t_short) / iters
    else:
        # still inverted: fall back to the un-differenced total — it
        # includes the fetch overhead, so it UNDERSTATES MFU (the
        # honest direction) and is labeled as such in the JSON
        dt = t_long / (2 + iters)
        timing = "fallback_total"

    # attn_flops_share (VERDICT r2 weak #3): MFU of a small model is not
    # predictive of 8B+mesh MFU; record where the FLOPs are so rounds are
    # comparable across configs.
    flops, n_params, attn_flops = llama_step_flops(cfg, batch, seq)
    tokens_per_s = batch * seq / dt
    peak = peak_flops_per_chip(getattr(dev, "device_kind", dev.platform))
    mfu = flops / dt / peak

    # XLA-derived accounting of the compiled step (ISSUE 11): re-lowers
    # the cached program from recorded avals — with the persistent
    # compilation cache on (worker enables it) the re-compile is a disk
    # hit. AFTER timing by construction; null on any failure (the
    # fallback chain stays exception-free). Reading caveat: Pallas
    # custom calls count ZERO flops, so with pallas_flash the analytic
    # number undercounts by ~attn_flops_share (profiler/cost.py).
    analytic_flops = peak_hbm_bytes = analytic_mfu = None
    try:
        rep = step.cost_report()
        progs = [p for p in rep["programs"] if "flops" in p]
        if progs:
            analytic_flops = float(progs[0]["flops"])
            peak_hbm_bytes = int(progs[0]["peak_bytes"])
            analytic_mfu = round(analytic_flops / dt / peak, 4)
    except Exception:
        pass

    # Collective-traffic accounting of the same compiled step (ISSUE
    # 12): payload bytes the step moves per mesh axis (zero / {} on a
    # single chip — the honest answer). Same contract as the cost
    # fields: AFTER timing, null on any failure, fallback chain and
    # exit-0 untouched.
    comm_bytes = comm_bytes_per_axis = None
    try:
        crep = step.comm_report()
        comm_bytes = int(crep["payload_bytes"])
        comm_bytes_per_axis = dict(crep["bytes_per_axis"])
    except Exception:
        pass

    return {
        "metric": "llama_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.40, 4),
        "tokens_per_sec_per_chip": round(tokens_per_s, 1),
        "step_time_s": round(dt, 4),
        "timing": timing,
        "n_params": int(n_params),
        "loss": float(np.asarray(loss._data)),
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "attention": "pallas_flash" if use_pallas else "xla_sdpa",
        "optimizer": ("fused_adamw_bf16_states" if fused_opt and on_tpu
                      else "fused_adamw" if fused_opt else "adamw"),
        "attn_flops_share": round(attn_flops / flops, 4),
        "analytic_flops": analytic_flops,
        "peak_hbm_bytes": peak_hbm_bytes,
        "analytic_mfu": analytic_mfu,
        "comm_bytes": comm_bytes,
        "comm_bytes_per_axis": comm_bytes_per_axis,
        "config": {"hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
                   "batch": batch, "seq": seq},
    }


def _error_json(msg: str, **extra) -> str:
    rec = {"metric": "llama_pretrain_mfu", "value": 0.0,
           "unit": "fraction_of_peak", "vs_baseline": 0.0,
           "error": msg[:400]}
    rec.update(extra)
    return json.dumps(rec)


def _enable_compile_cache():
    """Persistent XLA compilation cache (VERDICT r4 item 1: shrink the
    happy path so a short relay window at driver time still lands a
    number). The first in-session run pays the ~minutes 0.8B compile and
    populates .jax_cache/; the driver's later run of the SAME committed
    program is a disk hit and compiles in seconds. Importing jax here is
    safe — the TPU grant is only claimed at the first jax operation."""
    try:
        import jax
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass            # cache is an optimization, never a failure mode


def _maybe_inject_fault(i: int, kw: dict):
    """Test hook for the fallback chain (BENCH_FAULT_INJECT env var):
    'all' fails every attempt, 'pallas'/'xla' fail the matching
    attention paths, a digit fails that attempt index. Raises BEFORE
    run() so an injected attempt never touches jax or the TPU grant —
    the regression test drives the whole Pallas -> XLA -> shrink ->
    error-JSON chain without a device. Inert unless the env var is set."""
    spec = os.environ.get("BENCH_FAULT_INJECT", "")
    if not spec:
        return
    tokens = {t.strip() for t in spec.split(",") if t.strip()}
    hit = ("all" in tokens or str(i) in tokens
           or ("pallas" in tokens and kw.get("use_pallas"))
           or ("xla" in tokens and not kw.get("use_pallas")))
    if hit:
        raise RuntimeError(
            f"BENCH_FAULT_INJECT: injected failure of attempt {i} ({kw})")


def worker():
    """Runs the attempt chain. A watchdog thread guarantees a JSON line even
    if the TPU transport wedges mid-call (exceptions can be caught; hangs
    cannot — round 2's rc=124 was jax.devices() blocking on a dead relay)."""
    import threading
    import traceback

    _enable_compile_cache()

    state = {"phase": "import jax", "done": False}

    def _watchdog():
        time.sleep(max(DEADLINE_S - 60, 60))
        if not state["done"]:
            print(_error_json(
                f"bench watchdog fired after {DEADLINE_S - 60}s during phase "
                f"'{state['phase']}' (TPU transport likely wedged; axon relay "
                "dead => jax.devices() blocks forever)"), flush=True)
        # Exit either way: a worker that finished but wedges in interpreter
        # teardown (PJRT client talking to a dead relay) must still die
        # before the supervisor's SIGTERM/SIGKILL escalation.
        os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()

    # BENCH_FUSED_OPT=0 drops the fused-optimizer attempt so a live
    # window can A/B the round-4 configuration directly (chip_hour.sh's
    # bench re-run does exactly that — the chain degrades on EXCEPTIONS
    # only, so a fused config that runs but is slower must be caught by
    # comparing the two recorded lines, not trusted).
    attempts = []
    if os.environ.get("BENCH_FUSED_OPT", "1") != "0":
        attempts.append({"use_pallas": True, "shrink": 0, "fused_opt": True})
    attempts += [
        {"use_pallas": True, "shrink": 0},
        {"use_pallas": False, "shrink": 0},
        {"use_pallas": True, "shrink": 1},
        {"use_pallas": False, "shrink": 1},
    ]
    errors = []
    for i, kw in enumerate(attempts):
        state["phase"] = f"run({kw})"
        try:
            _maybe_inject_fault(i, kw)
            result = run(**kw)
            if errors:
                result["recovered_from"] = errors[-1][:300]
            print(json.dumps(result), flush=True)
            state["done"] = True  # after the flush: a watchdog firing
            return                # mid-print still emits its own record
        except Exception:
            errors.append(traceback.format_exc().strip().split("\n")[-1])
    print(_error_json("; ".join(e[:200] for e in errors[-2:])), flush=True)
    state["done"] = True


def _print_best_line(out: str) -> bool:
    """Print the best JSON record in the worker output; True if one found.
    Prefers a measured result over a watchdog/attempt error record (the
    worker can emit both when it finishes and then wedges in teardown)."""
    error_line = None
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not (isinstance(rec, dict) and "metric" in rec):
            continue
        if "error" not in rec:
            print(line)
            return True
        error_line = error_line or line
    if error_line is not None:
        print(error_line)
        return True
    return False


def main():
    """Supervisor: never imports jax, so it can never hang on the TPU
    transport. Runs the worker under a hard wall-clock budget and always
    prints exactly one JSON line and exits 0."""
    import subprocess

    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            stdout=subprocess.PIPE,
        )
        try:
            out_b, _ = proc.communicate(timeout=DEADLINE_S)
            fallback = f"worker exited rc={proc.returncode} with no JSON line"
        except subprocess.TimeoutExpired:
            # The worker's own watchdog fires 60s earlier, so reaching here
            # means even os._exit was starved. SIGTERM first: a SIGKILLed
            # TPU client leaks the single-client grant for minutes
            # (CLAUDE.md), which would wedge the driver's next gate too.
            proc.terminate()
            try:
                out_b, _ = proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                out_b, _ = proc.communicate()
            fallback = (f"worker exceeded hard deadline {DEADLINE_S}s and "
                        "was terminated (TPU transport wedged?)")
        out = (out_b or b"").decode("utf-8", "replace")
        if not _print_best_line(out):
            print(_error_json(fallback, tail=out[-300:]))
    except Exception as e:  # last resort: the gate must record something
        print(_error_json(f"supervisor failure: {e!r}"))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        main()
